"""Training driver: the reference main.py epoch loop, TPU-native.

Flow (reference main.py:230-287): per epoch — warm/joint phase select, train
epoch with mine/EM gates, test (+OoD when configured), conditional "nopush"
checkpoint; at push epochs — prototype projection, re-test, "push"
checkpoint; after the loop — top-M pruning, re-test, "prune" checkpoint.

Differences by design: checkpoints carry the FULL train state and `--resume`
continues bit-exactly (the reference deletes its model dir on restart,
main.py:31-33); the step runs SPMD over the configured mesh; metrics stream
to a local JSONL instead of wandb.

Fault tolerance (ISSUE 2): the epoch loop is wrapped in a recovery driver —
SIGTERM/SIGINT (or a chaos-simulated preemption) finishes the in-flight
step, saves an unconditional "preempt" checkpoint recording the mid-epoch
position, writes a PREEMPTED.json marker and returns cleanly, so the next
`--resume auto` invocation continues bit-exactly; `--max-bad-steps`
consecutive non-finite steps (updates already skipped inside the jitted
step) roll the run back to the last good checkpoint and replay it (the
loaders are (seed, epoch)-deterministic, so the replay is exact). Chaos
injection for drills comes from MGPROTO_CHAOS_* env knobs (see --help).
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
from typing import Optional

import jax
import numpy as np

from mgproto_tpu.cli.common import (
    add_train_args,
    config_from_args,
    describe,
    maybe_init_distributed,
)
from mgproto_tpu.config import Config
from mgproto_tpu.core.mgproto import prune_top_m
from mgproto_tpu.data import build_pipelines
from mgproto_tpu.engine import evaluate, evaluate_with_ood, push_prototypes
from mgproto_tpu.parallel import ShardedTrainer
from mgproto_tpu.resilience import chaos as chaos_mod
from mgproto_tpu.resilience import metrics as res_metrics
from mgproto_tpu.resilience import preemption
from mgproto_tpu.resilience.guard import DivergenceError, EpochGuard
from mgproto_tpu.utils import (
    Logger,
    MetricsWriter,
    restore_checkpoint,
    save_state_w_condition,
    timed_span,
)
from mgproto_tpu.telemetry import make_session, trace_span
from mgproto_tpu.utils.checkpoint import (
    adopt_checkpoint_train_config,
    apply_retention,
    checkpoint_name,
    find_latest_checkpoint,
    latest_checkpoint,
    load_metadata,
    save_checkpoint,
)
from mgproto_tpu.utils.log import profiler_trace


def _labeled(loader):
    """(images, labels, ids[, seeds]) loader stream -> (images, labels
    [, seeds]) step batches: ids are host bookkeeping, the augmentation
    seeds (u8 wire format) ride along to the device."""
    for batch in loader:
        yield (batch[0], batch[1]) + tuple(batch[3:])


def _test(trainer, state, test_loader, ood_loaders, log, score_rule="sum"):
    if ood_loaders:
        return evaluate_with_ood(
            trainer,
            state,
            _labeled(test_loader),
            [_labeled(o) for o in ood_loaders],
            score_rule=score_rule,
            log=log,
        )
    return evaluate(trainer, state, _labeled(test_loader), log=log)


def run_training(
    cfg: Config,
    resume: str = "",
    profile_dir: str = "",
    target_accu: float = 0.0,
    render_push: bool = True,
    telemetry_dir: str = "",
    telemetry: bool = True,
    max_bad_steps: int = 3,
    divergence_check_every: int = 8,
    max_rollbacks: int = 2,
    keep_last: int = 0,
    keep_best: int = 1,
    chaos=None,
    auto_tune: bool = False,
    profile_steps: str = "",
    profile_on_anomaly: bool = False,
    profile_out: str = "",
    barrier_timeout_s: float = 300.0,
    ckpt_format: str = "auto",
    straggler_threshold: float = 0.25,
    straggler_patience: int = 5,
):
    """Run the full schedule; returns (final_state, last_test_accuracy).

    Recovery knobs: `max_bad_steps` consecutive non-finite steps trigger a
    rollback to the last good checkpoint (0 disables; at most
    `max_rollbacks` before giving up); `divergence_check_every` is the
    host-sync cadence of the streak poll; `keep_last`/`keep_best` drive
    checkpoint retention (keep_last <= 0 keeps everything); `chaos` is an
    optional resilience.ChaosState for fault-injection drills (its one-shot
    bookkeeping intentionally survives across invocations, so a resumed
    run does not re-inject). A preemption (signal or chaos) checkpoints and
    returns early — check `resilience.get_handler().requested()`.

    Pod fault tolerance (ISSUE 9): under multi-host, `barrier_timeout_s`
    arms the guarded-barrier failure agreement over model_dir (a dead or
    wedged peer raises `BarrierTimeoutError` out of here after survivors
    write PEER_LOST.json and dump the flight recorder — main() turns that
    into exit code `PEER_LOST_EXIT_CODE` for the launch_pod.sh relaunch
    loop); `ckpt_format` picks the checkpoint protocol ('auto' = the
    coordinated sharded format when multi-host, the replicated orbax format
    otherwise).

    Fleet observatory (ISSUE 10): under multi-host every process runs a
    real TelemetrySession (host 0 keeps the canonical files, others write
    `.h<pid>` sidecars into the shared telemetry dir), the guarded
    barriers/collectives record wait histograms, and an obs.fleet
    SkewMonitor watches barrier-arrival skew — a host that stays the last
    arriver with skew-fraction EMA >= `straggler_threshold` for
    `straggler_patience` barriers captures a profiler trace of ITSELF and
    lands a `straggler_suspected` event on the flight recorder. Merge the
    per-host story with `mgproto-telemetry fleet <telemetry-dir>`."""
    # resolve --resume FIRST: a typo'd path must fail fast, before any
    # data-pipeline or device work happens. 'auto' resumes only from
    # manifest-verified checkpoints (torn saves and .tmp dirs never qualify)
    resume_path = None
    legacy_resume_note = ""
    if resume:
        if resume == "auto":
            resume_path = find_latest_checkpoint(cfg.model_dir)
            if resume_path is None:
                # pre-manifest (legacy) checkpoints never qualify for the
                # strict listing; silently retraining from scratch in the
                # same model_dir would discard their progress — fall back,
                # loudly
                resume_path = latest_checkpoint(cfg.model_dir)
                if resume_path is not None:
                    legacy_resume_note = (
                        f"note: resuming manifest-less legacy checkpoint "
                        f"{resume_path} (integrity cannot be verified; "
                        "newer saves carry a manifest)"
                    )
        else:
            resume_path = resume
            if not os.path.exists(resume_path):
                raise FileNotFoundError(resume_path)
    adoption_notes: list = []
    if resume_path:
        # resume under the checkpoint's own training-time settings: without
        # this, resuming e.g. a reference-stepping EM run without re-passing
        # the flag would silently switch EM math mid-training (ADVICE r3)
        cfg = adopt_checkpoint_train_config(
            cfg, resume_path, log=adoption_notes.append
        )

    os.makedirs(cfg.model_dir, exist_ok=True)
    from mgproto_tpu.parallel.multihost import (
        PEER_LOST_FILE,
        clear_barrier,
        configure_barrier,
        is_primary_host,
    )

    primary = is_primary_host()
    multihost = jax.process_count() > 1
    if primary:
        # this incarnation owns the previous one's failure marker: a stale
        # PEER_LOST.json would make the relaunch watchdog loop forever
        try:
            os.unlink(os.path.join(cfg.model_dir, PEER_LOST_FILE))
        except OSError:
            pass
    if ckpt_format not in ("auto", "sharded", "replicated"):
        raise ValueError(f"unknown ckpt_format {ckpt_format!r}")
    ckpt_sharded = {"auto": None, "sharded": True, "replicated": False}[
        ckpt_format
    ]
    # model_dir is SHARED under multi-host (the sharded checkpoint protocol
    # requires it); run-wide artifacts are host-0's, so non-primary hosts
    # write their log/metrics under a host-tagged name instead of
    # interleaving into host 0's files (ISSUE 9 side-effects audit)
    host_tag = "" if primary else f".h{jax.process_index()}"
    log = Logger(os.path.join(cfg.model_dir, f"train.log{host_tag}"))
    if legacy_resume_note:
        log(legacy_resume_note)
    for note in adoption_notes:
        # adoption ran before the Logger existed; the overrides it made are
        # exactly the decisions a run's own log must record
        log(note)
    metrics = MetricsWriter(
        os.path.join(cfg.model_dir, f"metrics.jsonl{host_tag}")
    )

    # HBM-budget auto-tuner (perf/planner.py): pick the run's (batch,
    # remat, prefetch, augment, async_bank) from the compiled-module memory
    # model BEFORE anything sizes itself off the config — the loaders and
    # the trainer below both read the plan's batch size. On a device with
    # no memory_stats (CPU) the v5e-class default budget applies, so the
    # plan is still a deliberate choice, never a trial-and-error OOM.
    autotune_outcome = None
    autotune_plan_meta = None
    if auto_tune:
        from mgproto_tpu.perf.planner import (
            PlanCandidate,
            apply_plan,
            autotune as run_autotune,
        )

        saved_plan = (
            (load_metadata(resume_path) or {}).get("autotune_plan")
            if resume_path else None
        )
        if saved_plan:
            # a resumed run must NOT re-plan: the budget environment may
            # have changed since the checkpoint, and a different batch
            # would desync the mid-epoch `batch_in_epoch` skip count (the
            # bit-exact-resume contract). Adopt the checkpointed plan
            # verbatim — it is recorded in every checkpoint's metadata.
            cand = PlanCandidate(
                batch=max(
                    int(saved_plan["batch"])
                    // max(jax.process_count(), 1), 1,
                ),
                remat_stages=tuple(saved_plan.get("remat_stages", ())),
                prefetch_depth=int(saved_plan.get("prefetch_depth", 0)),
                device_augment=bool(saved_plan.get("device_augment", False)),
                async_bank=bool(saved_plan.get("async_bank", False)),
            )
            cfg = apply_plan(cfg, cand)
            autotune_plan_meta = saved_plan
            log("autotune: resume adopts checkpointed plan "
                f"{saved_plan.get('name', '?')} (no re-planning)")
        else:
            cfg, autotune_outcome = run_autotune(cfg, log=log)
            if autotune_outcome.chosen is None:
                log("autotune: NO candidate plan fits the budget; keeping "
                    "the hand-set config (see telemetry meta for the "
                    "rejections)")
            else:
                autotune_plan_meta = autotune_outcome.chosen.to_meta()
                log("autotune: running "
                    f"{autotune_outcome.chosen.candidate.name}")

    log(describe(cfg))
    train_loader, push_loader, test_loader, ood_loaders = build_pipelines(cfg)
    steps_per_epoch = len(train_loader)
    trainer = ShardedTrainer(cfg, steps_per_epoch, donate=True)
    log(f"devices: {jax.device_count()}  mesh: {dict(trainer.mesh.shape)}")
    log(f"steps/epoch: {steps_per_epoch}")

    # telemetry: registry + tracing spans + step/health monitors, sunk to
    # <telemetry_dir> on host 0 only (see telemetry/session.py). Created
    # BEFORE the restore below so restore-time events (elastic_restores_
    # total) land in the registry this run actually sinks. The jit handles
    # are watched through a provider because ShardedTrainer builds its
    # sharded jits lazily.
    telem = make_session(
        telemetry_dir or os.path.join(cfg.model_dir, "telemetry"), telemetry
    )
    if telem:
        telem.monitor.watch(lambda: trainer.jit_handles)

    # a restore target skips the pretrained trunk load (about to be overwritten)
    state = trainer.init_state(
        jax.random.PRNGKey(cfg.seed), for_restore=bool(resume_path)
    )
    start_epoch = 0
    skip_batches = 0
    if resume_path:
        meta = load_metadata(resume_path) or {}
        state = trainer.prepare(restore_checkpoint(resume_path, state))
        if meta.get("stage") == "prune":
            log(f"run already complete ({resume_path}); nothing to resume")
            if telem:
                telem.close()
            clear_barrier()
            metrics.close()
            log.close()
            return state, float(meta.get("accuracy", 0.0))
        if meta.get("stage") == "preempt":
            # mid-epoch resume: re-enter the SAME epoch, skipping the
            # batches the preempted invocation already applied (the loader's
            # (seed, epoch)-deterministic order makes this bit-exact)
            start_epoch = int(meta.get("epoch", 0))
            skip_batches = int(meta.get("batch_in_epoch", 0))
            log(
                f"resumed preempted {resume_path} -> epoch {start_epoch} "
                f"(skipping {skip_batches} completed batches)"
            )
        else:
            start_epoch = int(meta.get("epoch", -1)) + 1
            log(f"resumed {resume_path} -> epoch {start_epoch}")
        if primary:  # run-wide marker: host 0's to clear (side-effects audit)
            preemption.clear_marker(cfg.model_dir)

    img_dir = os.path.join(cfg.model_dir, "img")
    # persisted so eval/interpret adopt the training-time trunk numerics
    # (p(x)/OoD thresholds are dtype-sensitive, SURVEY.md §7.3.5)
    run_meta = {
        "compute_dtype": cfg.model.compute_dtype,
        "arch": cfg.model.arch,
        # non-proxy aux losses have no params['proxies'] leaf: a restore
        # target must be built with the SAME aux_loss or the pytree
        # structures mismatch (core/state.py; adopt_checkpoint_train_config)
        "aux_loss": cfg.loss.aux_loss,
        # resuming a reference-stepping run without this flag would silently
        # switch EM math mid-training (trajectory change, no error)
        "em_reference_stepping": cfg.em.reference_stepping,
    }
    if autotune_plan_meta is not None:
        # every checkpoint carries the plan the run was sized with, so a
        # `--resume auto --auto_tune` invocation adopts it instead of
        # re-planning (see the autotune block above)
        run_meta["autotune_plan"] = autotune_plan_meta
    push_ds = push_loader.dataset
    accu = 0.0

    if telem:
        # run-config context next to the metric artifacts (summarize "meta")
        from mgproto_tpu.ops.fused_epilogue import resolve_fused_epilogue
        from mgproto_tpu.perf.planner import state_bytes_per_chip
        from mgproto_tpu.perf.precision import policy_meta

        # weak-scaling per-chip state accounting (ISSUE 14): what ONE chip
        # holds of the class-sharded bank and the per-param-sharded
        # optimizer moments under this run's mesh — shape math over the
        # LIVE state already in scope (no re-trace of the model init),
        # set on the gauges so the fleet table shows per-chip memory next
        # to the per-chip allgather bytes
        per_chip_state = state_bytes_per_chip(
            cfg, trainer.mesh.shape["model"], state=state
        )
        telem.observe_state_bytes(per_chip_state)
        telem.write_meta({
            **per_chip_state,
            **run_meta,
            # the full mixed-precision policy (perf/precision.py): what ran
            # in which dtype, next to the throughput it bought
            "precision_policy": policy_meta(trainer.precision),
            # RESOLVED (None = auto -> what this backend actually ran)
            "fused_epilogue": resolve_fused_epilogue(
                cfg.model.fused_epilogue, cfg.model.arch
            ),
            "prefetch_depth": cfg.data.prefetch_depth,
            "em_max_active_classes": trainer._em_cfg.max_active_classes,
            "remat": cfg.model.remat,
            "remat_stages": list(cfg.model.remat_stages),
            # input fast path: u8 wire + device augmentation tail
            "device_augment": trainer._device_augment,
            "wire_dtype": "uint8" if trainer._device_augment else "float32",
            "worker_backend": cfg.data.worker_backend,
            # async bank pipeline (one-step-stale EM when on)
            "async_bank": trainer.async_bank,
        })
        if autotune_outcome is not None:
            # chosen plan + per-candidate predicted peaks -> meta.json
            # "autotune", rejections -> autotune_plan_rejected_total
            telem.observe_autotune(autotune_outcome)

    # performance observatory (ISSUE 8): a fresh flight recorder for this
    # run, dumping next to the telemetry artifacts on divergence rollback,
    # preemption, or crash; plus the optional profiler capture window
    from mgproto_tpu.obs.flightrec import FlightRecorder, set_recorder
    from mgproto_tpu.obs.profiler import ProfilerWindow, parse_step_range

    recorder = FlightRecorder(
        dump_dir=telemetry_dir or os.path.join(cfg.model_dir, "telemetry")
    )
    prev_recorder = set_recorder(recorder)
    window = None
    if profile_steps or profile_on_anomaly or multihost:
        from mgproto_tpu.obs.stall import step_costs

        # multi-host: the window also exists (unarmed, zero cost) as the
        # straggler trigger's capture target — each host captures into its
        # own subdirectory so a shared-FS profile_out never collides
        base_out = profile_out or os.path.join(
            "evidence", f"trace_{os.path.basename(cfg.model_dir) or 'run'}"
        )
        window = ProfilerWindow(
            out_dir=base_out if primary else os.path.join(
                base_out, f"h{jax.process_index()}"
            ),
            steps=parse_step_range(profile_steps),
            on_anomaly=profile_on_anomaly,
            monitor=telem.monitor if telem else None,
            # the off-TPU degrade lowers THE production step program of
            # this run's config (obs/stall.py) — same helper the
            # auto-tuner measures with
            cost_provider=lambda: step_costs(cfg),
            log=log,
        )

    # fleet straggler detection (ISSUE 10): observe every guarded barrier's
    # arrival skew; a persistent last-arriver arms `window` on itself only.
    # Single-host runs never construct one — the zero-extra-work path.
    fleet_mon = None
    prev_skew_observer = None
    skew_observer_installed = False
    if multihost:
        from mgproto_tpu.obs.fleet import SkewMonitor
        from mgproto_tpu.parallel.multihost import set_skew_observer

        fleet_mon = SkewMonitor(
            process_id=jax.process_index(),
            window=window,
            monitor=telem.monitor if telem else None,
            threshold=straggler_threshold,
            patience=straggler_patience,
            log=log,
        )
        prev_skew_observer = set_skew_observer(fleet_mon.observe_barrier)
        skew_observer_installed = True

    # recovery wiring: preemption flag (signal handlers, if any, are
    # installed by main(); chaos raises the same flag), active chaos state,
    # multi-host stop agreement
    handler = preemption.get_handler()
    handler.reset()
    prev_chaos = None
    chaos_installed = chaos is not None
    if chaos_installed:
        prev_chaos = chaos_mod.set_active(chaos)

    if multihost and barrier_timeout_s and barrier_timeout_s > 0:
        # failure agreement: host-side collectives (preemption/epoch sync,
        # checkpoint commit) run through the guarded barrier from here on.
        # Configured HERE, after every fallible setup step (flag
        # validation, restore, autotune, pipeline build), so an exception
        # on the way in can never leak a configured process-global guard —
        # the try/finally below is the single owner of clear_barrier()
        configure_barrier(cfg.model_dir, barrier_timeout_s)
    log("start training")
    preempted = False
    rollbacks = 0
    try:
        epoch = start_epoch
        while epoch < cfg.schedule.num_train_epochs:
            # pin the loader's epoch so resume/rollback replays see the SAME
            # shuffle + augmentation streams an uninterrupted run would
            train_loader.epoch = epoch
            guard = EpochGuard(
                max_bad_steps=max_bad_steps,
                check_every=divergence_check_every,
                chaos=chaos_mod.get_active(),
                preemption=handler,
                already_done=skip_batches,
                multihost=multihost,
            )
            try:
                state, accu = _run_epoch(
                    cfg, trainer, state, epoch, start_epoch, profile_dir,
                    train_loader, test_loader, push_loader, push_ds,
                    ood_loaders, log, metrics, telem, run_meta, img_dir,
                    render_push, target_accu, guard, skip_batches,
                    window=window, ckpt_sharded=ckpt_sharded,
                    fleet=fleet_mon,
                )
            except DivergenceError as e:
                rollbacks += 1
                res_metrics.counter(res_metrics.ROLLBACKS).inc()
                recorder.record("rollback", epoch=epoch, error=str(e))
                dumped = recorder.maybe_dump("divergence_rollback")
                if dumped:
                    log(f"flight recorder dumped to {dumped}")
                if rollbacks > max_rollbacks:
                    log(f"rollback budget exhausted ({max_rollbacks}); giving up")
                    raise
                last_good = find_latest_checkpoint(cfg.model_dir)
                if last_good is None:
                    raise RuntimeError(
                        f"{e}; no checkpoint to roll back to — adjust the "
                        "config (lower lr / check the data) and restart"
                    ) from e
                log(f"{e}; rolling back to {last_good} "
                    f"({rollbacks}/{max_rollbacks})")
                target = trainer.init_state(
                    jax.random.PRNGKey(cfg.seed), for_restore=True
                )
                state = trainer.prepare(restore_checkpoint(last_good, target))
                rb_meta = load_metadata(last_good) or {}
                if rb_meta.get("stage") == "preempt":
                    epoch = int(rb_meta.get("epoch", 0))
                    skip_batches = int(rb_meta.get("batch_in_epoch", 0))
                else:
                    epoch = int(rb_meta.get("epoch", -1)) + 1
                    skip_batches = 0  # a stale mid-epoch skip would drop
                    # batches the restored state never saw
                continue  # replay from the restored position
            skip_batches = 0

            if guard.preempted:
                # preemption: the in-flight step finished inside train_epoch;
                # save the FULL state unconditionally (no accuracy gate — a
                # preempted epoch has no test score yet), record the
                # mid-epoch position, leave the marker, exit cleanly
                preempted = True
                name = checkpoint_name(epoch, "preempt", max(accu, 0.0))
                path = save_checkpoint(
                    cfg.model_dir, state, name,
                    metadata={
                        **run_meta,
                        "epoch": epoch,
                        "stage": "preempt",
                        "accuracy": accu,
                        "batch_in_epoch": guard.batches_done,
                        "reason": handler.reason or "",
                    },
                    sharded=ckpt_sharded,
                )
                res_metrics.counter(res_metrics.PREEMPTION_SAVES).inc()
                if primary:
                    preemption.write_marker(
                        cfg.model_dir, path, reason=handler.reason or "",
                        extra={"epoch": epoch,
                               "batch_in_epoch": guard.batches_done},
                    )
                if telem:
                    telem.flush(step=int(state.step),
                                extra={"event": "preemption"})
                recorder.record(
                    "preemption", epoch=epoch, batch=guard.batches_done,
                    reason=handler.reason or "",
                )
                recorder.maybe_dump("preemption")
                log(
                    f"preempted ({handler.reason}); saved {path} at epoch "
                    f"{epoch} batch {guard.batches_done}; resume with "
                    "--resume auto"
                )
                break

            if telem:
                telem.end_epoch(state, epoch=epoch, step=int(state.step))
            if keep_last > 0 and primary:
                # retention deletes from the SHARED model_dir: one deleter,
                # or hosts race each other's rmtree (side-effects audit)
                apply_retention(cfg.model_dir, keep_last, keep_best)
            epoch += 1

        if not preempted:
            # pruning (reference main.py:285-287); top_m <= K per class
            last_epoch = max(cfg.schedule.num_train_epochs - 1, start_epoch)
            top_m = min(
                cfg.schedule.prune_top_m, cfg.model.prototypes_per_class
            )
            state = state.replace(
                gmm=prune_top_m(
                    state.gmm, top_m,
                    renormalize=cfg.schedule.prune_renormalize,
                )
            )
            with trace_span("prune"):
                accu, test_results = _test(
                    trainer, state, test_loader, ood_loaders, log
                )
            metrics.write(
                int(state.step),
                {"epoch": last_epoch, "stage": "prune", **test_results},
            )
            save_state_w_condition(
                cfg.model_dir, state, last_epoch, "prune", accu, target_accu,
                metadata=run_meta, sharded=ckpt_sharded,
            )
            log("training done")
    except BaseException as e:
        # unhandled crash (incl. the exhausted-rollback re-raise): the ring
        # of recent steps/events is the post-mortem — dump it before the
        # exception propagates. A barrier timeout already dumped itself as
        # "peer_lost" (parallel/multihost._on_barrier_timeout).
        from mgproto_tpu.parallel.multihost import BarrierTimeoutError

        if not isinstance(e, BarrierTimeoutError):
            recorder.maybe_dump("crash")
        raise
    finally:
        clear_barrier()
        if skew_observer_installed:
            from mgproto_tpu.parallel.multihost import set_skew_observer

            set_skew_observer(prev_skew_observer)
        if window is not None:
            window.close()  # never leave a device trace open
        set_recorder(prev_recorder)
        if chaos_installed:
            chaos_mod.set_active(prev_chaos)
        if telem:
            telem.close()
        # release loader resources deterministically (worker pools, shm
        # slab ring) instead of leaving them to interpreter shutdown
        for loader in (train_loader, push_loader, test_loader, *ood_loaders):
            loader.close()
        metrics.close()
        log.close()
    return state, accu


def _run_epoch(
    cfg, trainer, state, epoch, start_epoch, profile_dir,
    train_loader, test_loader, push_loader, push_ds, ood_loaders,
    log, metrics, telem, run_meta, img_dir, render_push, target_accu,
    guard=None, skip_batches=0, window=None, ckpt_sharded=None, fleet=None,
):
    """One epoch of the reference main.py flow (train / test / conditional
    push), under an `epoch` tracing span so the stage spans nest.

    `guard` carries the recovery policy (divergence rollback raises out of
    here; a preemption stop returns early with the trained-so-far state and
    no test pass — the caller checkpoints it). `skip_batches` > 0 re-enters
    a preempted epoch mid-way."""
    import itertools

    with trace_span("epoch", epoch=epoch):
        log(f"epoch: \t{epoch}")
        flags = trainer.epoch_flags(state, epoch)
        log(f"use mining: \t{flags['use_mine']}")
        log(f"update GMM: \t{flags['update_gmm']}")

        trace = (
            profiler_trace(profile_dir)
            if (profile_dir and epoch == start_epoch)
            else contextlib.nullcontext()
        )
        batches = _labeled(train_loader)
        if skip_batches:
            # mid-epoch resume: drop the batches the preempted invocation
            # already applied (decode cost only; identical sample streams)
            batches = itertools.islice(batches, skip_batches, None)
        with timed_span(log, "train"), trace:
            state, last = trainer.train_epoch(
                state, batches, epoch,
                monitor=telem.monitor if telem else None,
                guard=guard,
                window=window,
                fleet=fleet,
            )
        if last is not None:
            m = jax.device_get(last._asdict())
            if telem:
                # em_active is the epoch max, em_compact_fallback the epoch
                # sum (engine/train.py train_epoch accumulators)
                telem.observe_em(
                    float(m["em_active"]), float(m["em_compact_fallback"])
                )
            if not np.isfinite(float(m["loss"])):
                if guard is None:
                    # failure detection the reference lacks (SURVEY.md
                    # §5.2/§5.3): with no guard wired in, stop with state
                    # intact rather than training on NaNs
                    last_ckpt = latest_checkpoint(cfg.model_dir)
                    hint = (
                        f"resume from {last_ckpt} with --resume auto"
                        if last_ckpt
                        else "no checkpoint was saved yet; adjust the config"
                    )
                    raise RuntimeError(
                        f"non-finite loss {float(m['loss'])} at epoch {epoch} "
                        f"(step {int(state.step)}); {hint}"
                    )
                # guarded: the update was skipped inside the step; counters
                # carry the event and the divergence policy decides rollback
                log(
                    f"\tnon-finite loss at step {int(state.step)} — update "
                    "skipped (divergence guard)"
                )
            log(
                "\tloss: {loss:.4f}  ce: {cross_entropy:.4f}  mine: {mine:.4f}"
                "  aux: {aux:.4f}  acc: {accuracy:.4f}  mem: {full_mem_ratio:.3f}".format(
                    **{k: float(v) for k, v in m.items()}
                )
            )
            metrics.write(
                int(state.step),
                {"epoch": epoch, **{k: float(v) for k, v in m.items()}},
            )
        if guard is not None and guard.preempted:
            # no test pass on a preempted epoch: the caller saves the state
            # and the resumed invocation finishes the epoch properly
            return state, 0.0

        with timed_span(log, "test"):
            accu, test_results = _test(
                trainer, state, test_loader, ood_loaders, log
            )
        metrics.write(int(state.step), {"epoch": epoch, **test_results})
        save_state_w_condition(
            cfg.model_dir, state, epoch, "nopush", accu, target_accu,
            metadata=run_meta, sharded=ckpt_sharded,
        )

        if epoch >= cfg.schedule.push_start and epoch in cfg.schedule.push_epochs():
            with timed_span(log, "push"):
                state, push_result = push_prototypes(
                    trainer,
                    state,
                    iter(push_loader),
                    save_dir=img_dir if render_push else None,
                    epoch=epoch,
                    load_image=lambda i: push_ds.load(i)[0],
                )
            from mgproto_tpu.parallel.multihost import is_primary_host

            if is_primary_host():
                # nearest-training-patch table for the explanation path
                # (mgproto-export --explain reads it; engine/push.py) —
                # run-wide artifact, so host 0's to write (side-effects
                # audit, PR 9)
                import json as _json

                from mgproto_tpu.engine.push import provenance_dict

                with open(
                    os.path.join(cfg.model_dir, "push_provenance.json"), "w"
                ) as f:
                    _json.dump(
                        {"epoch": epoch, **provenance_dict(push_result)}, f
                    )
            accu, test_results = _test(
                trainer, state, test_loader, ood_loaders, log
            )
            metrics.write(
                int(state.step), {"epoch": epoch, "stage": "push", **test_results}
            )
            save_state_w_condition(
                cfg.model_dir, state, epoch, "push", accu, target_accu,
                metadata=run_meta, sharded=ckpt_sharded,
            )

    return state, accu


CHAOS_ENV_HELP = """\
chaos-injection env knobs (fault drills; all off by default):
  MGPROTO_CHAOS_SEED            seed for the deterministic fault schedule
  MGPROTO_CHAOS_LOADER_IO_RATE  fraction of sample loads that raise IOError
  MGPROTO_CHAOS_LOADER_IO_FAILS attempts each chosen sample fails (1 =
                                transient, heals on first retry)
  MGPROTO_CHAOS_NAN_AT_STEP     NaN-poison the batch of this global step
  MGPROTO_CHAOS_PREEMPT_AT_STEP simulate SIGTERM at this global step
  MGPROTO_CHAOS_CKPT_FAILS      fail the first N checkpoint writes
  MGPROTO_CHAOS_KILL_HOST_AT    this process DIES hard (os._exit) when the
                                batch for this global step is drawn — pod
                                host-crash drill (survivors must exit 75
                                via the guarded-barrier timeout)
  MGPROTO_CHAOS_WEDGE_HOST_AT   same, but the process HANGS (stuck host)
  MGPROTO_CHAOS_SLOW_HOST_MS    non-fatal straggler: the targeted process
                                sleeps this many ms before EVERY step (the
                                fleet skew monitor must name it)
  MGPROTO_CHAOS_HOST_INDEX      restrict kill/wedge/slow to this
                                jax.process_index() (-1: any process whose
                                environment carries the knob)
serving-side knobs (MGPROTO_CHAOS_SERVE_*): see `mgproto-serve --help`
"""


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(
        description="Train MGProto-TPU (reference main.py equivalent)",
        epilog=CHAOS_ENV_HELP,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    add_train_args(p)
    args = p.parse_args(argv)
    maybe_init_distributed(args)
    cfg = config_from_args(args)
    # graceful preemption: SIGTERM/SIGINT finish the in-flight step,
    # checkpoint, and exit 0 (the ONLY signal-handler install site)
    if not args.no_preempt_handlers:
        preemption.install_handlers()
    chaos_plan = chaos_mod.plan_from_env()
    chaos_state = chaos_mod.ChaosState(chaos_plan) if chaos_plan else None
    from mgproto_tpu.parallel.multihost import (
        PEER_LOST_EXIT_CODE,
        BarrierTimeoutError,
    )

    try:
        run_training(
            cfg,
            resume=args.resume,
            profile_dir=args.profile_dir,
            target_accu=args.target_accu,
            telemetry_dir=args.telemetry_dir,
            telemetry=not args.no_telemetry,
            max_bad_steps=args.max_bad_steps,
            divergence_check_every=args.divergence_check_every,
            max_rollbacks=args.max_rollbacks,
            keep_last=args.keep_last,
            keep_best=args.keep_best,
            chaos=chaos_state,
            auto_tune=args.auto_tune,
            profile_steps=args.profile_steps,
            profile_on_anomaly=args.profile_on_anomaly,
            profile_out=args.profile_out,
            barrier_timeout_s=args.barrier_timeout_s,
            ckpt_format=args.ckpt_format,
            straggler_threshold=args.straggler_threshold,
            straggler_patience=args.straggler_patience,
        )
    except BarrierTimeoutError as e:
        # failure agreement: the marker + flight-recorder dump are already
        # on disk. Exit HARD with the distinct status the pod launcher's
        # watchdog answers with relaunch-from-last-commit — a graceful
        # sys.exit would hang in jax.distributed's atexit teardown waiting
        # for the very peer that just died.
        sys.stderr.write(f"peer lost: {e}\n")
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(PEER_LOST_EXIT_CODE)
    # a preempted run exits 0: the scheduler sees a clean shutdown and the
    # marker file + checkpoint make the next invocation resume bit-exactly


if __name__ == "__main__":
    main()
