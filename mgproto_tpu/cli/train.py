"""Training driver: the reference main.py epoch loop, TPU-native.

Flow (reference main.py:230-287): per epoch — warm/joint phase select, train
epoch with mine/EM gates, test (+OoD when configured), conditional "nopush"
checkpoint; at push epochs — prototype projection, re-test, "push"
checkpoint; after the loop — top-M pruning, re-test, "prune" checkpoint.

Differences by design: checkpoints carry the FULL train state and `--resume`
continues bit-exactly (the reference deletes its model dir on restart,
main.py:31-33); the step runs SPMD over the configured mesh; metrics stream
to a local JSONL instead of wandb.
"""

from __future__ import annotations

import argparse
import contextlib
import os
from typing import Optional

import jax
import numpy as np

from mgproto_tpu.cli.common import (
    add_train_args,
    config_from_args,
    describe,
    maybe_init_distributed,
)
from mgproto_tpu.config import Config
from mgproto_tpu.core.mgproto import prune_top_m
from mgproto_tpu.data import build_pipelines
from mgproto_tpu.engine import evaluate, evaluate_with_ood, push_prototypes
from mgproto_tpu.parallel import ShardedTrainer
from mgproto_tpu.utils import (
    Logger,
    MetricsWriter,
    latest_checkpoint,
    restore_checkpoint,
    save_state_w_condition,
    timed_span,
)
from mgproto_tpu.telemetry import make_session, trace_span
from mgproto_tpu.utils.checkpoint import (
    adopt_checkpoint_train_config,
    load_metadata,
)
from mgproto_tpu.utils.log import profiler_trace


def _labeled(loader):
    for images, labels, _ids in loader:
        yield images, labels


def _test(trainer, state, test_loader, ood_loaders, log, score_rule="sum"):
    if ood_loaders:
        return evaluate_with_ood(
            trainer,
            state,
            _labeled(test_loader),
            [_labeled(o) for o in ood_loaders],
            score_rule=score_rule,
            log=log,
        )
    return evaluate(trainer, state, _labeled(test_loader), log=log)


def run_training(
    cfg: Config,
    resume: str = "",
    profile_dir: str = "",
    target_accu: float = 0.0,
    render_push: bool = True,
    telemetry_dir: str = "",
    telemetry: bool = True,
):
    """Run the full schedule; returns (final_state, last_test_accuracy)."""
    # resolve --resume FIRST: a typo'd path must fail fast, before any
    # data-pipeline or device work happens
    resume_path = None
    if resume:
        resume_path = latest_checkpoint(cfg.model_dir) if resume == "auto" else resume
        if resume != "auto" and not os.path.exists(resume_path):
            raise FileNotFoundError(resume_path)
    adoption_notes: list = []
    if resume_path:
        # resume under the checkpoint's own training-time settings: without
        # this, resuming e.g. a reference-stepping EM run without re-passing
        # the flag would silently switch EM math mid-training (ADVICE r3)
        cfg = adopt_checkpoint_train_config(
            cfg, resume_path, log=adoption_notes.append
        )

    os.makedirs(cfg.model_dir, exist_ok=True)
    log = Logger(os.path.join(cfg.model_dir, "train.log"))
    for note in adoption_notes:
        # adoption ran before the Logger existed; the overrides it made are
        # exactly the decisions a run's own log must record
        log(note)
    metrics = MetricsWriter(os.path.join(cfg.model_dir, "metrics.jsonl"))

    log(describe(cfg))
    train_loader, push_loader, test_loader, ood_loaders = build_pipelines(cfg)
    steps_per_epoch = len(train_loader)
    trainer = ShardedTrainer(cfg, steps_per_epoch, donate=True)
    log(f"devices: {jax.device_count()}  mesh: {dict(trainer.mesh.shape)}")
    log(f"steps/epoch: {steps_per_epoch}")

    # a restore target skips the pretrained trunk load (about to be overwritten)
    state = trainer.init_state(
        jax.random.PRNGKey(cfg.seed), for_restore=bool(resume_path)
    )
    start_epoch = 0
    if resume_path:
        meta = load_metadata(resume_path) or {}
        state = trainer.prepare(restore_checkpoint(resume_path, state))
        if meta.get("stage") == "prune":
            log(f"run already complete ({resume_path}); nothing to resume")
            metrics.close()
            log.close()
            return state, float(meta.get("accuracy", 0.0))
        start_epoch = int(meta.get("epoch", -1)) + 1
        log(f"resumed {resume_path} -> epoch {start_epoch}")

    img_dir = os.path.join(cfg.model_dir, "img")
    # persisted so eval/interpret adopt the training-time trunk numerics
    # (p(x)/OoD thresholds are dtype-sensitive, SURVEY.md §7.3.5)
    run_meta = {
        "compute_dtype": cfg.model.compute_dtype,
        "arch": cfg.model.arch,
        # non-proxy aux losses have no params['proxies'] leaf: a restore
        # target must be built with the SAME aux_loss or the pytree
        # structures mismatch (core/state.py; adopt_checkpoint_train_config)
        "aux_loss": cfg.loss.aux_loss,
        # resuming a reference-stepping run without this flag would silently
        # switch EM math mid-training (trajectory change, no error)
        "em_reference_stepping": cfg.em.reference_stepping,
    }
    push_ds = push_loader.dataset
    accu = 0.0

    # telemetry: registry + tracing spans + step/health monitors, sunk to
    # <telemetry_dir> on host 0 only (see telemetry/session.py). The jit
    # handles are watched through a provider because ShardedTrainer builds
    # its sharded jits lazily.
    telem = make_session(
        telemetry_dir or os.path.join(cfg.model_dir, "telemetry"), telemetry
    )
    if telem:
        telem.monitor.watch(lambda: trainer.jit_handles)

    log("start training")
    try:
        for epoch in range(start_epoch, cfg.schedule.num_train_epochs):
            state, accu = _run_epoch(
                cfg, trainer, state, epoch, start_epoch, profile_dir,
                train_loader, test_loader, push_loader, push_ds, ood_loaders,
                log, metrics, telem, run_meta, img_dir, render_push,
                target_accu,
            )
            if telem:
                telem.end_epoch(state, epoch=epoch, step=int(state.step))

        # pruning (reference main.py:285-287); top_m can't exceed K per class
        last_epoch = max(cfg.schedule.num_train_epochs - 1, start_epoch)
        top_m = min(cfg.schedule.prune_top_m, cfg.model.prototypes_per_class)
        state = state.replace(
            gmm=prune_top_m(
                state.gmm, top_m, renormalize=cfg.schedule.prune_renormalize
            )
        )
        with trace_span("prune"):
            accu, test_results = _test(
                trainer, state, test_loader, ood_loaders, log
            )
        metrics.write(
            int(state.step),
            {"epoch": last_epoch, "stage": "prune", **test_results},
        )
        save_state_w_condition(
            cfg.model_dir, state, last_epoch, "prune", accu, target_accu,
            metadata=run_meta,
        )
        log("training done")
    finally:
        if telem:
            telem.close()
        metrics.close()
        log.close()
    return state, accu


def _run_epoch(
    cfg, trainer, state, epoch, start_epoch, profile_dir,
    train_loader, test_loader, push_loader, push_ds, ood_loaders,
    log, metrics, telem, run_meta, img_dir, render_push, target_accu,
):
    """One epoch of the reference main.py flow (train / test / conditional
    push), under an `epoch` tracing span so the stage spans nest."""
    with trace_span("epoch", epoch=epoch):
        log(f"epoch: \t{epoch}")
        flags = trainer.epoch_flags(state, epoch)
        log(f"use mining: \t{flags['use_mine']}")
        log(f"update GMM: \t{flags['update_gmm']}")

        trace = (
            profiler_trace(profile_dir)
            if (profile_dir and epoch == start_epoch)
            else contextlib.nullcontext()
        )
        with timed_span(log, "train"), trace:
            state, last = trainer.train_epoch(
                state, _labeled(train_loader), epoch,
                monitor=telem.monitor if telem else None,
            )
        if last is not None:
            m = jax.device_get(last._asdict())
            if not np.isfinite(float(m["loss"])):
                # failure detection the reference lacks (SURVEY.md §5.2/§5.3):
                # stop with state intact rather than training on NaNs; the
                # last good checkpoint in model_dir is the resume point
                last_ckpt = latest_checkpoint(cfg.model_dir)
                hint = (
                    f"resume from {last_ckpt} with --resume auto"
                    if last_ckpt
                    else "no checkpoint was saved yet; adjust the config"
                )
                raise RuntimeError(
                    f"non-finite loss {float(m['loss'])} at epoch {epoch} "
                    f"(step {int(state.step)}); {hint}"
                )
            log(
                "\tloss: {loss:.4f}  ce: {cross_entropy:.4f}  mine: {mine:.4f}"
                "  aux: {aux:.4f}  acc: {accuracy:.4f}  mem: {full_mem_ratio:.3f}".format(
                    **{k: float(v) for k, v in m.items()}
                )
            )
            metrics.write(
                int(state.step),
                {"epoch": epoch, **{k: float(v) for k, v in m.items()}},
            )

        with timed_span(log, "test"):
            accu, test_results = _test(
                trainer, state, test_loader, ood_loaders, log
            )
        metrics.write(int(state.step), {"epoch": epoch, **test_results})
        save_state_w_condition(
            cfg.model_dir, state, epoch, "nopush", accu, target_accu,
            metadata=run_meta,
        )

        if epoch >= cfg.schedule.push_start and epoch in cfg.schedule.push_epochs():
            with timed_span(log, "push"):
                state, _ = push_prototypes(
                    trainer,
                    state,
                    iter(push_loader),
                    save_dir=img_dir if render_push else None,
                    epoch=epoch,
                    load_image=lambda i: push_ds.load(i)[0],
                )
            accu, test_results = _test(
                trainer, state, test_loader, ood_loaders, log
            )
            metrics.write(
                int(state.step), {"epoch": epoch, "stage": "push", **test_results}
            )
            save_state_w_condition(
                cfg.model_dir, state, epoch, "push", accu, target_accu,
                metadata=run_meta,
            )

    return state, accu


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(
        description="Train MGProto-TPU (reference main.py equivalent)"
    )
    add_train_args(p)
    args = p.parse_args(argv)
    maybe_init_distributed(args)
    cfg = config_from_args(args)
    run_training(
        cfg,
        resume=args.resume,
        profile_dir=args.profile_dir,
        target_accu=args.target_accu,
        telemetry_dir=args.telemetry_dir,
        telemetry=not args.no_telemetry,
    )


if __name__ == "__main__":
    main()
