"""Command-line entry points (reference main.py / eval_*.py / run.sh).

`python -m mgproto_tpu.cli.train`  — full training driver
`python -m mgproto_tpu.cli.evaluate` — test / OoD / interpretability metrics
`python -m mgproto_tpu.cli.prep`  — offline dataset preparation
`python -m mgproto_tpu.cli.telemetry` — summarize a run's telemetry dir
"""

from mgproto_tpu.cli.common import DATASET_PRESETS, config_from_args

__all__ = ["DATASET_PRESETS", "config_from_args"]
