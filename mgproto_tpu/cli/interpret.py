"""Interpretability metric driver: consistency / stability / purity.

Reference: eval_consistency.py, eval_stability.py, eval_purity.py — three
near-identical scripts, folded into one CLI with a --metric flag. Loads a
checkpoint, runs the CUB test split through the gt-class activation
collector, and prints the score(s).
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

import jax

from mgproto_tpu.cli.common import (
    add_train_args,
    config_from_args,
    maybe_init_distributed,
)
from mgproto_tpu.data import Cub2011Eval, DataLoader, ood_transform
from mgproto_tpu.data.cub_parts import CubParts
from mgproto_tpu.engine.interpretability import (
    collect_gt_activations,
    evaluate_consistency,
    evaluate_purity,
    evaluate_stability,
    make_gt_act_fn,
)
from mgproto_tpu.parallel import ShardedTrainer
from mgproto_tpu.utils import latest_checkpoint, restore_checkpoint
from mgproto_tpu.utils.checkpoint import adopt_checkpoint_train_config


def build_eval_loader(cfg, cub_root: str) -> DataLoader:
    """Squash-resize eval loader over the CUB test split — the reference
    eval scripts' transform (interpretability.py:29-33 Resize((img,img)),
    NOT the center-crop test pipeline), so part coordinates scaled by
    width/height line up with the activation grid. Sharded by process;
    shared with `mgproto-trust interp` (the sharded evaluators)."""
    dataset = Cub2011Eval(
        cub_root, train=False, transform=ood_transform(cfg.model.img_size)
    )
    return DataLoader(
        dataset,
        cfg.data.test_batch_size,
        num_workers=cfg.data.num_workers,
        # resize-only pipeline: not GIL-bound, thread workers suffice;
        # per-process shard: collect_gt_activations allgathers rows
        shard_index=jax.process_index(),
        shard_count=jax.process_count(),
    )


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(
        description="Prototype interpretability metrics (reference eval_*.py)"
    )
    add_train_args(p)
    p.add_argument(
        "--metric",
        default="all",
        choices=["consistency", "stability", "purity", "all"],
    )
    p.add_argument(
        "--cub_root",
        required=True,
        help="CUB_200_2011 root (images.txt, parts/, images/)",
    )
    p.add_argument("--checkpoint", default="auto")
    p.add_argument("--half_size", type=int, default=36,
                   help="box half-size for consistency/stability (purity uses 16)")
    p.add_argument("--purity_half_size", type=int, default=16)
    p.add_argument("--purity_top_k", type=int, default=10)
    p.add_argument("--export_csv", default="",
                   help="also write the per-prototype top-K patch CSV "
                        "(method-agnostic purity interchange format)")
    args = p.parse_args(argv)
    maybe_init_distributed(args)
    cfg = config_from_args(args)

    parts = CubParts(args.cub_root)
    loader = build_eval_loader(cfg, args.cub_root)

    path = (
        latest_checkpoint(cfg.model_dir)
        if args.checkpoint == "auto"
        else args.checkpoint
    )
    if not path:
        raise FileNotFoundError(f"no checkpoint in {cfg.model_dir}")
    cfg = adopt_checkpoint_train_config(cfg, path, log=print)

    trainer = ShardedTrainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(cfg.seed), for_restore=True)
    state = trainer.prepare(restore_checkpoint(path, state))
    print(f"loaded {path}")

    c = cfg.model.num_classes
    # one compiled forward + one clean test-set pass shared by all metrics
    act_fn = make_gt_act_fn(trainer.model)
    clean = collect_gt_activations(trainer, state, iter(loader), act_fn=act_fn)
    results = {}
    if args.metric in ("consistency", "all"):
        results["consistency"] = evaluate_consistency(
            trainer, state, None, parts, c, half_size=args.half_size,
            activations=clean,
        )
    if args.metric in ("stability", "all"):
        results["stability"] = evaluate_stability(
            trainer, state, lambda: iter(loader), parts, c,
            half_size=args.half_size, activations=clean, act_fn=act_fn,
        )
    if args.metric in ("purity", "all"):
        mean, std = evaluate_purity(
            trainer, state, None, parts, c,
            half_size=args.purity_half_size, top_k=args.purity_top_k,
            activations=clean,
        )
        results["purity"] = mean
        results["purity_std"] = std
    if args.export_csv and jax.process_index() == 0:
        # any metric selection (clean activations are already collected and
        # allgathered); process 0 only — every process holds the full data
        # and concurrent writers would corrupt a shared-filesystem path
        from mgproto_tpu.engine.interpretability import (
            export_prototype_patches_csv,
        )

        results["csv_rows"] = export_prototype_patches_csv(
            args.export_csv, trainer, state, None, c,
            half_size=args.purity_half_size, top_k=args.purity_top_k,
            activations=clean,
        )
        results["csv"] = args.export_csv
    print(json.dumps(results))


if __name__ == "__main__":
    main()
