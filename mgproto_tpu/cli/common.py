"""Shared CLI plumbing: dataset presets + argparse -> Config.

The reference splits configuration between settings.py module constants and
argparse flags (reference settings.py:1-52, main.py:19-27). Here every knob
lands in one typed `Config`; presets fill per-dataset class counts and
directory conventions (reference settings.py:8-24)."""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Dict

from mgproto_tpu.config import (
    Config,
    DataConfig,
    EMConfig,
    LossConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    ScheduleConfig,
)

# num_classes per dataset (reference: CUB settings.py:2; Cars/Dogs/Pets from
# the paper's experimental suite, README.md:34-45 + preprocess_data scripts)
DATASET_PRESETS: Dict[str, Dict] = {
    "CUB": {"num_classes": 200, "sub": "cub200_cropped"},
    "Cars": {"num_classes": 196, "sub": "stanford_cars_cropped"},
    "Dogs": {"num_classes": 120, "sub": "stanford_dogs"},
    "Pets": {"num_classes": 37, "sub": "oxford_pets"},
    # stretch config (SURVEY.md §7.2.9): 1000-class density/EM/memory shard
    # over the mesh's 'model' axis (--mesh_model), keeping per-chip density
    # tiles and EM statistics local to each class shard
    "ImageNet": {"num_classes": 1000, "sub": "imagenet"},
}


def maybe_init_distributed(args: argparse.Namespace) -> None:
    """Honor --distributed before any other jax call (parallel/mesh.py
    docstring); strict: an explicitly requested multi-host run must fail
    loudly rather than silently degrade to single-host."""
    if args.distributed:
        from mgproto_tpu.parallel.mesh import initialize_distributed

        initialize_distributed(strict=True)


def add_train_args(p: argparse.ArgumentParser) -> None:
    # reference main.py:19-27 flags (minus -gpuid: device selection is
    # JAX_PLATFORMS / mesh shape here)
    p.add_argument("--dataset", default="CUB", choices=sorted(DATASET_PRESETS))
    p.add_argument("--arch", default="resnet34")
    p.add_argument("--aux_loss", default="proxy_anchor",
                   choices=["proxy_anchor", "proxy_nca", "ms", "contrastive",
                            "triplet", "npair"])
    p.add_argument("--aux_emb_sz", type=int, default=32)
    p.add_argument("--mem_sz", type=int, default=800)
    p.add_argument("--mine_level", type=int, default=20)
    # paths (reference settings.py:8-19; explicit flags replace hard-coding)
    p.add_argument("--data_root", default="./datasets")
    p.add_argument("--train_dir", default="")
    p.add_argument("--test_dir", default="")
    p.add_argument("--push_dir", default="")
    p.add_argument("--ood_dir", action="append", default=[],
                   help="OoD test set root (repeatable)")
    p.add_argument("--model_dir", default="./saved_models")
    # shapes / schedule
    p.add_argument("--img_size", type=int, default=224)
    p.add_argument("--num_classes", type=int, default=0,
                   help="0 = dataset preset")
    p.add_argument("--protos_per_class", type=int, default=10)
    p.add_argument("--proto_dim", type=int, default=64)
    p.add_argument("--batch_size", type=int, default=80)
    p.add_argument("--epochs", type=int, default=120)
    p.add_argument("--warm_epochs", type=int, default=0)
    p.add_argument("--mine_start", type=int, default=40)
    p.add_argument("--gmm_start", type=int, default=35)
    p.add_argument("--push_start", type=int, default=100)
    p.add_argument("--push_every", type=int, default=10)
    p.add_argument("--prune_top_m", type=int, default=8)
    p.add_argument(
        "--prune_renormalize", action="store_true",
        help="renormalize kept priors after pruning (beyond-parity; "
             "preserves per-class mixture mass, recompute OoD thresholds)",
    )
    p.add_argument(
        "--em_reference_stepping", action="store_true",
        help="reference-exact EM: sequential per-class Adam steps incl. the "
             "torch moment-decay drift (slower; default is the vmapped "
             "all-class step — see core/em.py)",
    )
    p.add_argument("--no_pretrained", action="store_true")
    # default matches ModelConfig so pre-existing f32 checkpoints evaluate
    # under the numerics they trained with; launch_tpu.sh opts into bf16
    p.add_argument("--compute_dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="trunk compute dtype (params/density stay f32)")
    p.add_argument("--fused_scoring", action="store_true", default=None,
                   help="force the Pallas fused density+top-T kernel on "
                        "(default: auto — on for TPU with an unsharded "
                        "class axis, off elsewhere)")
    p.add_argument("--no_fused_scoring", dest="fused_scoring",
                   action="store_false",
                   help="force the XLA scoring path")
    p.add_argument("--fused_epilogue", action="store_true", default=None,
                   help="force the Pallas BN+shortcut-add+ReLU block "
                        "epilogue on (default: auto — on for TPU resnet "
                        "trunks, off elsewhere; ops/fused_epilogue.py)")
    p.add_argument("--no_fused_epilogue", dest="fused_epilogue",
                   action="store_false",
                   help="force the plain XLA block epilogue")
    p.add_argument("--remat", action="store_true",
                   help="checkpoint backbone blocks (HBM for FLOPs)")
    p.add_argument("--remat_stages", default="",
                   help="comma-separated backbone stages to remat "
                        "selectively (e.g. 'layer1' — the cheap-but-wide "
                        "112^2 stage; densenets use 'denseblockN'); "
                        "--remat overrides with full-trunk remat")
    p.add_argument("--num_workers", type=int, default=8)
    p.add_argument("--worker_backend", default="thread",
                   choices=["thread", "process"],
                   help="train-loader workers: 'process' (spawn pool) scales "
                        "the augmentation math past the GIL on many-core "
                        "hosts")
    p.add_argument("--device_augment", action="store_true", default=None,
                   help="force the uint8 wire format + device augmentation "
                        "tail on: the train loader ships u8 geometry-only "
                        "samples (4x fewer bytes per hop) and flip + "
                        "brightness/contrast/saturation jitter + normalize "
                        "run inside the jitted step (default: auto — on "
                        "for TPU, off elsewhere)")
    p.add_argument("--no_device_augment", dest="device_augment",
                   action="store_false",
                   help="force the classic f32 host augmentation pipeline")
    p.add_argument("--prefetch-depth", "--prefetch_depth",
                   dest="prefetch_depth", type=int, default=2,
                   help="device-prefetch depth: batches held in flight so "
                        "the next H2D copy overlaps the current step "
                        "(data/loader.py device_prefetch; each extra unit "
                        "costs one batch of HBM)")
    p.add_argument("--em_max_active", type=int, default=-1,
                   help="compact dirty-class EM width (core/em.py): -1 auto "
                        "(min(classes, global batch)), 0 dense path, >0 "
                        "explicit slab width")
    p.add_argument("--fused_estep", action="store_true", default=None,
                   help="force the fused Pallas E-step kernel on (default: "
                        "auto — on for TPU, off elsewhere)")
    p.add_argument("--no_fused_estep", dest="fused_estep",
                   action="store_false",
                   help="force the XLA E-step path")
    p.add_argument("--async_bank", action="store_true", default=None,
                   help="force the async bank pipeline on: memory enqueue "
                        "+ EM run as their own program dispatched one step "
                        "behind the trunk (scoring sees one-step-stale "
                        "prototypes; bank buffers donated in place). "
                        "Default: auto — on for TPU, off elsewhere")
    p.add_argument("--no_async_bank", dest="async_bank",
                   action="store_false",
                   help="force the synchronous monolithic step")
    p.add_argument("--auto_tune", action="store_true",
                   help="HBM-budget auto-tuner (perf/planner.py): compile "
                        "candidate (batch, remat, prefetch, augment, "
                        "async_bank) plans, read XLA's memory analysis, and "
                        "run the largest plan that fits the device HBM with "
                        "margin (MGPROTO_HBM_MARGIN, default 0.08; budget "
                        "override MGPROTO_HBM_BUDGET_BYTES). The chosen "
                        "plan + every candidate's predicted peak land in "
                        "telemetry meta.json")
    p.add_argument("--seed", type=int, default=0)
    # runtime
    p.add_argument("--distributed", action="store_true",
                   help="multi-host: jax.distributed.initialize() before "
                        "device use (TPU pods auto-detect coordinator)")
    p.add_argument("--mesh_data", type=int, default=-1,
                   help="data-axis size (-1 = all devices)")
    p.add_argument("--mesh_model", type=int, default=1)
    p.add_argument("--resume", default="",
                   help="checkpoint path, or 'auto' for the latest "
                        "manifest-verified checkpoint in model_dir "
                        "(preempted runs resume mid-epoch, bit-exactly)")
    # fault tolerance (resilience subsystem; README 'Fault tolerance')
    p.add_argument("--max-bad-steps", "--max_bad_steps",
                   dest="max_bad_steps", type=int, default=3,
                   help="divergence guard: consecutive non-finite steps "
                        "(updates are skipped in-step) before rolling back "
                        "to the last good checkpoint (0 disables rollback)")
    p.add_argument("--divergence_check_every", type=int, default=8,
                   help="host-sync cadence (steps) of the divergence streak "
                        "poll and multi-host preemption agreement")
    p.add_argument("--max_rollbacks", type=int, default=2,
                   help="divergence rollbacks before the run gives up")
    p.add_argument("--keep_last", type=int, default=0,
                   help="checkpoint retention: keep only the newest N "
                        "checkpoints (plus --keep_best by accuracy); "
                        "0 keeps everything")
    p.add_argument("--keep_best", type=int, default=1,
                   help="always-retained best-accuracy checkpoints when "
                        "--keep_last is active")
    p.add_argument("--no_preempt_handlers", action="store_true",
                   help="do not install SIGTERM/SIGINT graceful-preemption "
                        "handlers (default: installed; first signal "
                        "checkpoints + exits 0, second kills)")
    # pod fault tolerance (ISSUE 9; README 'Pod fault tolerance')
    p.add_argument("--barrier_timeout_s", type=float, default=300.0,
                   help="multi-host failure agreement: host-side agreement "
                        "collectives run through a heartbeat-file barrier "
                        "over model_dir; a peer missing past this timeout "
                        "makes survivors dump the flight recorder, write "
                        "PEER_LOST.json and exit 75 so launch_pod.sh "
                        "relaunches from the last committed checkpoint "
                        "(<= 0 disables; single-process runs ignore it)")
    # fleet observatory (ISSUE 10): straggler detection under multi-host —
    # the guarded barrier's arrival skew, EMA'd as a fraction of step time;
    # a host that is the persistent last-arriver arms a targeted profiler
    # capture on ITSELF only (obs/fleet.py)
    p.add_argument("--straggler_threshold", type=float, default=0.25,
                   help="skew-fraction EMA (arrival skew / step time) above "
                        "which a persistent last-arriver host is flagged "
                        "as a straggler and captures a trace of itself "
                        "(<= 0 disables detection; the skew gauge still "
                        "updates; single-process runs ignore it)")
    p.add_argument("--straggler_patience", type=int, default=5,
                   help="consecutive last-arriver barriers above the "
                        "threshold before the straggler trigger fires")
    p.add_argument("--ckpt_format", default="auto",
                   choices=["auto", "sharded", "replicated"],
                   help="checkpoint format: 'sharded' = coordinated "
                        "per-host shard files + COMMIT marker (elastic "
                        "restore onto any mesh), 'replicated' = the "
                        "single-file orbax format funneled through host 0, "
                        "'auto' = sharded when multi-host")
    p.add_argument("--profile_dir", default="",
                   help="write a jax.profiler trace of one epoch here")
    # performance observatory (obs/profiler.py): step-scoped capture
    # windows, far cheaper than the epoch-wide --profile_dir trace
    p.add_argument("--profile_steps", default="",
                   help="arm a profiler capture window for this step range "
                        "('120:130', or a bare step for one step); off-TPU "
                        "the window degrades to a cost-analysis-only "
                        "capture (obs/profiler.py)")
    p.add_argument("--profile_on_anomaly", action="store_true",
                   help="arm profiler capture automatically on anomalies: "
                        "step-time spike vs EMA, mid-run jit recompile, or "
                        "loader-wait fraction over threshold; traces land "
                        "under --profile_out")
    p.add_argument("--profile_out", default="",
                   help="capture-window output dir (default: "
                        "evidence/trace_<model_dir basename>)")
    # telemetry (metric registry + tracing spans + step/health monitors);
    # both dash and underscore spellings resolve to the same dest
    p.add_argument("--telemetry-dir", "--telemetry_dir", dest="telemetry_dir",
                   default="",
                   help="telemetry output dir (metrics.prom / metrics.jsonl /"
                        " health.jsonl / trace.json; default: "
                        "<model_dir>/telemetry); summarize with "
                        "`mgproto-telemetry <dir>`")
    p.add_argument("--no-telemetry", "--no_telemetry", dest="no_telemetry",
                   action="store_true",
                   help="disable the telemetry subsystem entirely")
    p.add_argument("--target_accu", type=float, default=0.0,
                   help="save checkpoints only above this test accuracy")


def config_from_args(args: argparse.Namespace) -> Config:
    preset = DATASET_PRESETS[args.dataset]
    num_classes = args.num_classes or preset["num_classes"]
    root = os.path.join(args.data_root, preset["sub"])
    # reference directory conventions (settings.py:9-13): train_cropped_augmented /
    # train_cropped (push) / test_cropped
    train_dir = args.train_dir or os.path.join(root, "train_cropped_augmented")
    push_dir = args.push_dir or os.path.join(root, "train_cropped")
    test_dir = args.test_dir or os.path.join(root, "test_cropped")
    return Config(
        model=ModelConfig(
            arch=args.arch,
            img_size=args.img_size,
            num_classes=num_classes,
            prototypes_per_class=args.protos_per_class,
            proto_dim=args.proto_dim,
            sz_embedding=args.aux_emb_sz,
            mine_T=args.mine_level,
            mem_capacity=args.mem_sz,
            pretrained=not args.no_pretrained,
            compute_dtype=args.compute_dtype,
            fused_scoring=args.fused_scoring,
            fused_epilogue=args.fused_epilogue,
            remat=args.remat,
            remat_stages=tuple(
                s for s in args.remat_stages.split(",") if s
            ),
        ),
        em=EMConfig(
            reference_stepping=args.em_reference_stepping,
            max_active_classes=args.em_max_active,
            fused_estep=args.fused_estep,
            async_bank=args.async_bank,
        ),
        optim=OptimConfig(),
        schedule=ScheduleConfig(
            num_train_epochs=args.epochs,
            num_warm_epochs=args.warm_epochs,
            mine_start=args.mine_start,
            update_gmm_start=args.gmm_start,
            push_start=args.push_start,
            push_every=args.push_every,
            prune_top_m=args.prune_top_m,
            prune_renormalize=args.prune_renormalize,
        ),
        loss=LossConfig(aux_loss=args.aux_loss),
        data=DataConfig(
            dataset=args.dataset,
            train_dir=train_dir,
            test_dir=test_dir,
            train_push_dir=push_dir,
            ood_dirs=tuple(args.ood_dir),
            train_batch_size=args.batch_size,
            test_batch_size=args.batch_size,
            train_push_batch_size=args.batch_size,
            num_workers=args.num_workers,
            worker_backend=args.worker_backend,
            prefetch_depth=args.prefetch_depth,
            device_augment=args.device_augment,
        ),
        mesh=MeshConfig(data=args.mesh_data, model=args.mesh_model),
        seed=args.seed,
        model_dir=args.model_dir,
    )


def describe(cfg: Config) -> str:
    return "\n".join(
        f"{f.name}: {getattr(cfg, f.name)}" for f in dataclasses.fields(cfg)
    )
