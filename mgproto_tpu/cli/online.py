"""Online-learning driver: the drift drill and the drift status view.

`mgproto-online drill` runs the seeded, virtual-clock drift drill (the
ISSUE 11 deliverable): class-conditional traffic through the real serving
plane, a hermetic EM bootstrap so served accuracy is real, an injected
distribution shift (`--drift-kind shift`) or a brand-new class claiming a
padded class_bucket slot (`--drift-kind new_class`), the continual-learning
plane (trusted capture -> background consolidation -> drift monitor ->
recalibrate + blue/green republish) closing the loop, and ONE JSON record
of the whole story — detection-before-correction timestamps, before/during/
after accuracy + p(x) curves, poison accounting, zero-dropped / zero-
recompile proofs:

    mgproto-online drill --out evidence/drift_drill.json

The committed record is gated by `mgproto-telemetry check --drift-drill
evidence/drift_drill.json` (cli/telemetry.py re-derives every verdict from
the raw numbers). `mgproto-online status DIR` renders a telemetry dir's
drift section (the same data `mgproto-telemetry summarize` shows, scoped).

Hermetic: tiny model, CPU, seeded — no dataset, no network, no TPU.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from typing import Optional

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _load_load_test():
    """scripts/load_test.py as a module (scripts/ is repo-level, not a
    package — the same path trick the tests use)."""
    path = os.path.join(_REPO, "scripts", "load_test.py")
    if not os.path.isfile(path):
        raise SystemExit(
            f"cannot find scripts/load_test.py under {_REPO}; the drill "
            "driver runs from a repo checkout"
        )
    spec = importlib.util.spec_from_file_location("mgproto_load_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_drill(
    seed: int = 0,
    drift_kind: str = "shift",
    drift_at: int = 120,
    drift_magnitude: float = 0.25,
    phases: str = "2x40,4x40,4x40",
    capture_percentile: float = 10.0,
    poison_rate: Optional[float] = None,
    class_bucket: int = 8,
    accuracy_window: int = 40,
) -> dict:
    """The drift drill as a dict record (drift_drill.json schema:
    evidence/README.md). Importable — tests run the acceptance drill
    through this exact function."""
    lt = _load_load_test()
    result = lt.run_load_test(
        seed=seed,
        phases=lt.parse_phases(phases),
        online=True,
        drift_at=drift_at,
        drift_kind=drift_kind,
        drift_magnitude=drift_magnitude,
        capture_percentile=capture_percentile,
        poison_rate=poison_rate,
        class_bucket=class_bucket,
        accuracy_window=accuracy_window,
    )
    result["drift_drill"] = True
    # self-gate: the same derivations `mgproto-telemetry check
    # --drift-drill` applies, stored for the reader (check re-derives,
    # never trusts these)
    from mgproto_tpu.cli.telemetry import drift_drill_gates

    result["gates"] = drift_drill_gates(result)
    return result


def drill_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="mgproto-online drill",
        description="Seeded drift drill: inject shift, detect via p(x), "
                    "correct via recalibrate + blue/green republish",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--drift-kind", choices=("shift", "new_class"),
                   default="shift")
    p.add_argument("--drift-at", type=int, default=120,
                   help="request index at which the distribution shifts")
    p.add_argument("--drift-magnitude", type=float, default=0.25)
    p.add_argument("--phases", default="2x40,4x40,4x40",
                   help="comma list of DURxRPS storm phases")
    p.add_argument("--capture-percentile", type=float, default=10.0)
    p.add_argument("--poison-rate", type=float, default=None,
                   help="low-p(x) mislabeled chaos fraction (default: "
                        "MGPROTO_CHAOS_ONLINE_POISON_RATE)")
    p.add_argument("--class-bucket", type=int, default=8)
    p.add_argument("--accuracy-window", type=int, default=40)
    p.add_argument("--out", default="",
                   help="write the record here (e.g. "
                        "evidence/drift_drill.json)")
    args = p.parse_args(argv)
    record = run_drill(
        seed=args.seed,
        drift_kind=args.drift_kind,
        drift_at=args.drift_at,
        drift_magnitude=args.drift_magnitude,
        phases=args.phases,
        capture_percentile=args.capture_percentile,
        poison_rate=args.poison_rate,
        class_bucket=args.class_bucket,
        accuracy_window=args.accuracy_window,
    )
    line = json.dumps(record, sort_keys=True)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if record["gates"]["ok"] else 1


def status_main(argv=None) -> int:
    from mgproto_tpu.cli.telemetry import _fmt, summarize

    p = argparse.ArgumentParser(
        prog="mgproto-online status",
        description="Render a telemetry dir's online-learning drift "
                    "section",
    )
    p.add_argument("dir", help="telemetry dir (or a run dir containing "
                               "telemetry/)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    if not os.path.isdir(args.dir):
        raise SystemExit(f"not a directory: {args.dir}")
    summary = summarize(args.dir)
    drift = summary.get("drift")
    if drift is None:
        # only possible for a telemetry dir written before the online
        # family existed — current sessions always pre-register it
        raise SystemExit(
            f"no online_*/drift_* series under {args.dir} (pre-online "
            "telemetry dir?)"
        )
    if args.json:
        print(json.dumps(drift, indent=2))
        return 0
    width = max(len(k) for k in drift)
    for k, v in drift.items():
        if isinstance(v, dict):
            v = " ".join(
                f"{kk}={_fmt(vv)}" for kk, vv in sorted(v.items())
            ) or "-"
        print(f"{k:<{width}}  {_fmt(v)}")
    return 0


def main(argv: Optional[list] = None) -> Optional[int]:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "drill":
        return drill_main(argv[1:])
    if argv and argv[0] == "status":
        return status_main(argv[1:])
    p = argparse.ArgumentParser(
        description="Online MGProto driver (subcommands: drill, status)"
    )
    p.parse_args(argv if argv else ["--help"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
