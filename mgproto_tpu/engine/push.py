"""Prototype projection ("push"): snap each Gaussian prototype mean to its
nearest real training patch, for interpretability.

Reference: push.py:14-231. Two passes there: (1) a python scan recording, for
every prototype, every same-class image's best patch (spatial argmin of
distance = argmax of density); (2) a greedy pass in prototype order that sorts
each prototype's candidates by distance and takes the best patch from an image
no other prototype has claimed yet, copying that patch's feature vector into
the prototype mean (push.py:193-198).

TPU-native redesign: pass 1 is one jitted device function per batch — for
each image, the spatial argmax + feature gather for its gt class's K
prototypes only ([B,K] work instead of the reference's 2000-iteration python
loop per batch, push.py:125-158). The candidate tensor streamed to host is
tiny ([B, K] + [B, K, d]). Pass 2's image-dedup greedy is inherently
sequential (SURVEY.md §7.3.3) and runs on host over the collected candidates.
Rendering (heatmap/bbox crops, push.py:202-226) re-forwards only the chosen
images, batched.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mgproto_tpu.core.mgproto import GMMState, patch_log_densities
from mgproto_tpu.core.state import TrainState
from mgproto_tpu.telemetry.tracing import trace_span
from mgproto_tpu.utils import vis
from mgproto_tpu.utils.images import preprocess_input


class PushResult(NamedTuple):
    """Per-prototype projection record ([C, K] leading axes).

    pushed:       bool — whether a patch was found (classes with no images
                  in the push set keep their learned mean, as in the
                  reference where the candidate list stays empty).
    image_id:     int — global dataset index of the source image (-1 if not
                  pushed); the dedup key (reference uses file names).
    spatial_idx:  int — flattened latent (h*W + w) of the chosen patch.
    log_prob:     float — the patch's log-density under the prototype.
    """

    pushed: np.ndarray
    image_id: np.ndarray
    spatial_idx: np.ndarray
    log_prob: np.ndarray


def provenance_dict(result: PushResult) -> Dict[str, list]:
    """A PushResult as the JSON-able nearest-training-patch table the
    explanation path consumes (engine/export.py::explain_table, served as
    ServeResponse.explain `source_patch` blocks): flat [C*K] image id /
    latent spatial index / patch log-density per prototype, -1 ids for
    prototypes the push set never covered."""
    return {
        "image_id": [int(v) for v in result.image_id.reshape(-1)],
        "spatial_idx": [int(v) for v in result.spatial_idx.reshape(-1)],
        "log_prob": [float(v) for v in result.log_prob.reshape(-1)],
    }


def load_push_provenance(model_dir: str) -> Optional[Dict]:
    """The run's `push_provenance.json` (written by cli/train's push
    stage) as a dict, or None when the run never pushed. The ONE loader
    both explanation faces use (`mgproto-export --explain` and the live
    `mgproto-serve --explain`), so the schema cannot drift between them."""
    import json

    path = os.path.join(model_dir, "push_provenance.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def make_scan_fn(model) -> Callable:
    """Jitted pass-1 kernel: (params, batch_stats, gmm, images, labels) ->
    (val [B,K], idx [B,K], fvec [B,K,d]) — each image's best patch per
    gt-class prototype. `images` must already be normalized."""

    def fn(params, batch_stats, gmm: GMMState, images, labels):
        variables = {"params": params["net"], "batch_stats": batch_stats}
        proto_map, _ = model.apply(variables, images, train=False)
        log_prob, feat = patch_log_densities(proto_map, gmm)  # [B,C,K,H,W]
        b, c, k, h, w = log_prob.shape
        sel = labels[:, None, None, None, None]
        lp = jnp.take_along_axis(log_prob, sel, axis=1)[:, 0]  # [B,K,H,W]
        flat = lp.reshape(b, k, h * w)
        idx = jnp.argmax(flat, axis=-1)  # [B,K]
        val = jnp.max(flat, axis=-1)  # [B,K]
        fv = feat.reshape(b, h * w, -1)  # [B,HW,d]
        fvec = jnp.take_along_axis(fv, idx[:, :, None], axis=1)  # [B,K,d]
        return val, idx, fvec

    return jax.jit(fn)


def _greedy_assign(
    labels: np.ndarray,  # [N]
    image_ids: np.ndarray,  # [N]
    vals: np.ndarray,  # [N, K]
    idxs: np.ndarray,  # [N, K]
    fvecs: np.ndarray,  # [N, K, d]
    num_classes: int,
) -> Tuple[np.ndarray, PushResult]:
    """Pass 2: reference push.py:160-228 dedup semantics — prototypes claim
    images greedily in prototype order (c*K + k), best candidate first, one
    distinct image per prototype across the WHOLE prototype set."""
    k_per_class = vals.shape[1]
    d = fvecs.shape[-1]
    new_means = np.zeros((num_classes, k_per_class, d), np.float32)
    pushed = np.zeros((num_classes, k_per_class), bool)
    out_img = np.full((num_classes, k_per_class), -1, np.int64)
    out_idx = np.full((num_classes, k_per_class), -1, np.int64)
    out_lp = np.full((num_classes, k_per_class), -np.inf, np.float64)

    by_class: Dict[int, np.ndarray] = {}
    for c in range(num_classes):
        by_class[c] = np.where(labels == c)[0]

    used: set = set()
    for c in range(num_classes):
        rows = by_class[c]
        for k in range(k_per_class):
            if rows.size == 0:
                continue
            order = rows[np.argsort(-vals[rows, k])]  # best density first
            for r in order:
                img = int(image_ids[r])
                if img in used:
                    continue
                used.add(img)
                new_means[c, k] = fvecs[r, k]
                pushed[c, k] = True
                out_img[c, k] = img
                out_idx[c, k] = int(idxs[r, k])
                out_lp[c, k] = float(vals[r, k])
                break
    return new_means, PushResult(pushed, out_img, out_idx, out_lp)


@jax.jit
def _write_back(g_means, nm, pm):
    """Module-level jit (compiles once across push epochs)."""
    return jnp.where(pm[:, :, None], nm, g_means)


def push_prototypes(
    trainer,
    state: TrainState,
    batches: Iterable[Tuple[np.ndarray, np.ndarray, np.ndarray]],
    save_dir: Optional[str] = None,
    epoch: Optional[int] = None,
    load_image: Optional[Callable[[int], np.ndarray]] = None,
    normalize: Callable[[np.ndarray], np.ndarray] = preprocess_input,
) -> Tuple[TrainState, PushResult]:
    """Project every prototype mean onto its nearest training patch.

    Args:
      trainer:  engine Trainer (supplies the model; state carries params).
      state:    current TrainState; returns a new one with projected means.
      batches:  iterable of (images [B,H,W,3] in [0,1] UNNORMALIZED,
                labels [B], image_ids [B]) — the reference's push loader
                (resize-only, no normalization, main.py:111-116).
      save_dir: if set, render 3 files per pushed prototype
                (reference push.py:202-226); requires `load_image`.
      load_image: image_id -> [H,W,3] float in [0,1] (push-transform sized).
    """
    scan = make_scan_fn(trainer.model)

    # host-local copies of the weights/GMM: the scan below is a per-process
    # local jit over this process's loader shard, so cross-host-sharded
    # state must be replicated first (parallel/multihost.py)
    from mgproto_tpu.parallel.multihost import allgather_rows, fetch_replicated

    params_h, stats_h, gmm_h = fetch_replicated(
        (state.params, state.batch_stats, state.gmm),
        getattr(trainer, "mesh", None),
    )

    all_labels: List[np.ndarray] = []
    all_ids: List[np.ndarray] = []
    all_vals: List[np.ndarray] = []
    all_idxs: List[np.ndarray] = []
    all_fvecs: List[np.ndarray] = []
    with trace_span("push/scan") as scan_attrs:
        for images, labels, image_ids in batches:
            images = normalize(np.asarray(images, np.float32))
            val, idx, fvec = scan(
                params_h,
                stats_h,
                gmm_h,
                jnp.asarray(images),
                jnp.asarray(labels, jnp.int32),
            )
            all_labels.append(np.asarray(labels))
            all_ids.append(np.asarray(image_ids))
            all_vals.append(jax.device_get(val))
            all_idxs.append(jax.device_get(idx))
            all_fvecs.append(jax.device_get(fvec))
        scan_attrs["batches"] = len(all_labels)

    if not all_labels:
        raise ValueError("push set is empty")

    # candidates from every process's shard (equal shapes; sentinel rows have
    # label -1 and are never selected by _greedy_assign)
    labels = allgather_rows(np.concatenate(all_labels))
    image_ids = allgather_rows(np.concatenate(all_ids))
    vals = allgather_rows(np.concatenate(all_vals))
    idxs = allgather_rows(np.concatenate(all_idxs))
    fvecs = allgather_rows(np.concatenate(all_fvecs))

    c = state.gmm.num_classes
    with trace_span("push/assign") as assign_attrs:
        new_means, result = _greedy_assign(
            labels, image_ids, vals, idxs, fvecs, c
        )
        assign_attrs["pushed"] = int(result.pushed.sum())

    # write-back inside jit: state.gmm.means may be a cross-host-sharded
    # global array (outside-jit jnp.where cannot touch those); new_means /
    # pushed are identical on every process after the gather, so they enter
    # as replicated operands and the output keeps the means' sharding
    means = _write_back(
        state.gmm.means,
        jnp.asarray(new_means),
        jnp.asarray(result.pushed),
    )
    new_state = state.replace(gmm=state.gmm._replace(means=means))

    if save_dir is not None:
        if load_image is None:
            raise ValueError("save_dir requires load_image")
        out = (
            os.path.join(save_dir, f"epoch-{epoch}")
            if epoch is not None
            else save_dir
        )
        vis.makedir(out)
        with trace_span("push/render"):
            _render(trainer, new_state, result, load_image, normalize, out)

    return new_state, result


def _render(
    trainer,
    state: TrainState,
    result: PushResult,
    load_image: Callable[[int], np.ndarray],
    normalize: Callable[[np.ndarray], np.ndarray],
    out_dir: str,
) -> None:
    """Per pushed prototype: original+bbox, self-activation overlay+bbox,
    and the cropped high-activation region (reference push.py:202-226)."""

    def act_fn(params, batch_stats, gmm, image, c):
        variables = {"params": params["net"], "batch_stats": batch_stats}
        proto_map, _ = trainer.model.apply(
            variables, image[None], train=False
        )
        log_prob, _ = patch_log_densities(proto_map, gmm)  # [1,C,K,H,W]
        return jnp.exp(log_prob[0, c])  # [K, H, W] densities (act = -dist)

    act_jit = jax.jit(act_fn)

    c_total, k_per_class = result.pushed.shape
    for c in range(c_total):
        if not result.pushed[c].any():
            continue
        img_cache: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for k in range(k_per_class):
            if not result.pushed[c, k]:
                continue
            img_id = int(result.image_id[c, k])
            if img_id not in img_cache:
                raw = np.asarray(load_image(img_id), np.float32)
                acts = jax.device_get(
                    act_jit(
                        state.params,
                        state.batch_stats,
                        state.gmm,
                        jnp.asarray(normalize(raw)),
                        c,
                    )
                )
                img_cache[img_id] = (raw, acts)
            raw, acts = img_cache[img_id]
            j = c * k_per_class + k  # reference's flat prototype index
            up = vis.upsample_activation(acts[k], raw.shape[:2])
            y0, y1, x0, x1 = vis.find_high_activation_crop(up)
            vis.imsave_with_bbox(
                os.path.join(out_dir, f"{j}prototype-img-original.jpg"),
                raw, y0, y1, x0, x1,
            )
            vis.imsave_with_bbox(
                os.path.join(
                    out_dir, f"{j}prototype-img-original_with_self_act.jpg"
                ),
                vis.heatmap_overlay(raw, up), y0, y1, x0, x1,
            )
            vis.imsave(
                os.path.join(out_dir, f"{j}prototype-img.jpg"),
                raw[y0:y1, x0:x1],
            )
