from mgproto_tpu.engine.evaluate import (
    evaluate,
    evaluate_with_ood,
    prototype_pair_distance,
)
from mgproto_tpu.engine.push import PushResult, push_prototypes
from mgproto_tpu.engine.train import Trainer, TrainMetrics

__all__ = [
    "Trainer",
    "TrainMetrics",
    "PushResult",
    "push_prototypes",
    "evaluate",
    "evaluate_with_ood",
    "prototype_pair_distance",
]
