from mgproto_tpu.engine.push import PushResult, push_prototypes
from mgproto_tpu.engine.train import Trainer, TrainMetrics

__all__ = ["Trainer", "TrainMetrics", "PushResult", "push_prototypes"]
