from mgproto_tpu.engine.train import Trainer, TrainMetrics

__all__ = ["Trainer", "TrainMetrics"]
