"""Test + OoD evaluation drivers.

Reference: train_and_test.py:100-242. `_testing` = accuracy + mean CE +
mean prototype pair distance; `_testing_with_OoD` additionally derives an
OoD threshold from the ID test set's generative scores p(x) = sum_c p(x|c)
and reports, per OoD set, the fraction predicted in-distribution (the
reference calls this FPR95_*; its threshold is the 5th ID percentile).

All device math is log-domain (`log_px` = logsumexp of class log-likelihoods);
percentile/threshold bookkeeping is host-side numpy over per-sample scalars,
exactly as the reference does it on CPU (train_and_test.py:195-200).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mgproto_tpu.core.mgproto import GMMState
# canonical home moved to the jax-free trust package (ISSUE 15) so the
# check CLI can re-derive per-pair AUROC from committed raw scores without
# jax; re-exported here unchanged for every existing caller
from mgproto_tpu.trust.auroc import binary_auroc  # noqa: F401


def prototype_pair_distance(gmm: GMMState) -> float:
    """Mean pairwise squared distance over ALL prototypes (reference
    train_and_test.py:148-151 + utils/helpers.py:13-14 `list_of_distances`,
    which includes the zero diagonal in the mean)."""
    p = np.asarray(gmm.means, np.float64).reshape(-1, gmm.means.shape[-1])
    sq = (p**2).sum(-1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (p @ p.T)
    return float(np.maximum(d2, 0.0).mean())


def _run_eval(
    trainer, state, batches
) -> Tuple[np.ndarray, np.ndarray, float, int, np.ndarray]:
    """Shared loop: returns (per-sample log p(x), per-sample correct flags,
    summed CE over batches, batch count, per-sample class log-likelihood
    matrix [N, C]) over the GLOBAL dataset — everything downstream scoring
    needs from ONE forward pass per batch.

    Batches may be bare image arrays (unlabeled OoD), (images, labels), or
    (images, labels, ids) — the loader's padded sentinel rows carry label -1
    and are dropped host-side so jitted shapes stay static. Under multi-host,
    each process feeds its loader shard, reads back only its addressable rows
    (`host_local_rows`), and the per-sample arrays are allgathered so every
    process computes identical global metrics (reference semantics: one
    process saw everything, train_and_test.py:100-242)."""
    from mgproto_tpu.parallel.multihost import allgather_rows, host_local_rows

    log_pxs, corrects, valids, logit_rows = [], [], [], []
    ce_total, n_batches = 0.0, 0
    for batch in batches:
        if isinstance(batch, tuple):
            images, labels = batch[0], batch[1]
        else:
            images, labels = batch, None
        labels_dev = None if labels is None else jnp.asarray(labels)
        out = trainer.eval_step(state, jnp.asarray(images), labels_dev)
        batch_log_px = host_local_rows(out.log_px)
        batch_correct = host_local_rows(out.correct)
        logits = host_local_rows(out.logits).astype(np.float64)
        if labels is None:
            valid = np.ones(batch_log_px.shape[0], bool)
        else:
            valid = np.asarray(labels) >= 0
            lse = _logsumexp(logits)
            lbl = np.where(valid, np.asarray(labels), 0)
            if valid.any():
                ce_total += float(
                    np.mean((lse - logits[np.arange(len(lbl)), lbl])[valid])
                )
                n_batches += 1
        log_pxs.append(batch_log_px)
        corrects.append(batch_correct)
        valids.append(valid)
        logit_rows.append(logits)
    # raw per-shard concatenations have EQUAL shapes on every process (the
    # loaders pad all shards to the same batch count, data/loader.py), so the
    # cross-process gather is a plain row concat; the validity mask travels
    # with the data and sentinel rows are dropped globally afterwards.
    n_c = int(state.gmm.num_classes)
    log_px = allgather_rows(np.concatenate(log_pxs) if log_pxs else np.zeros((0,)))
    correct = allgather_rows(
        np.concatenate(corrects) if corrects else np.zeros((0,), bool)
    )
    valid = allgather_rows(
        np.concatenate(valids) if valids else np.zeros((0,), bool)
    ).astype(bool)
    logits_all = allgather_rows(
        np.concatenate(logit_rows) if logit_rows else np.zeros((0, n_c))
    )
    if jax.process_count() > 1:
        from mgproto_tpu.parallel.multihost import allgather_sum

        ce_total = allgather_sum(ce_total)
        n_batches = int(allgather_sum(float(n_batches)))
    return (
        log_px[valid],
        correct[valid].astype(bool),
        ce_total,
        n_batches,
        logits_all[valid],
    )


def evaluate(trainer, state, batches, log=print) -> Tuple[float, Dict]:
    """Accuracy pass (reference `_testing`, train_and_test.py:100-157).

    `batches` yields (images, labels) host arrays. Returns
    (accuracy, {'acc', 'cross_entropy', 'p_avg_pair_dist'})."""
    _, correct, ce_total, n_batches, _ = _run_eval(trainer, state, batches)
    acc = float(correct.mean()) if correct.size else 0.0
    pdist = prototype_pair_distance(state.gmm)
    log(f"\ttest acc: \t\t{acc * 100}%")
    log(f"\tp dist pair: \t{pdist}")
    return acc, {
        "acc": acc,
        "cross_entropy": ce_total / max(n_batches, 1),
        "p_avg_pair_dist": pdist,
    }


def evaluate_with_ood(
    trainer,
    state,
    id_batches,
    ood_batch_iters: Sequence[Iterable],
    percentile: float = 5.0,
    score_rule: str = "sum",
    log=print,
) -> Tuple[float, Dict]:
    """OoD pass (reference `_testing_with_OoD`, train_and_test.py:161-238).

    Quirk preserved from the reference: the threshold is the `percentile`-th
    percentile of SUM_c p(x|c) over the ID set (train_and_test.py:196-197),
    but each OoD sample is flagged in-distribution when its MEAN_c p(x|c)
    exceeds that threshold (train_and_test.py:213,227) — a C-fold asymmetry
    kept for behavior parity. Reported `fpr` per OoD set = fraction of OoD
    samples predicted in-distribution at the ID-`percentile` operating point.

    Beyond the reference: `AUROC_i` per OoD set — the threshold-free metric
    the paper's OoD tables report. Computed on the log p(x) scores (rank
    statistics are monotone-invariant, so log vs exp and the C-fold
    asymmetry don't matter here). Also `score_variants_i`: AUROC under
    alternative scoring rules (max-over-classes, temperature-scaled p(x) —
    `ood_score_variants`), from the SAME forward pass.

    `score_rule` selects the OPERATING-POINT rule (threshold + FPR):
    "sum" is the inherited reference behavior above (exp space, for
    parity); "max" thresholds max_c log p(x|c) symmetrically (no C-fold
    asymmetry) in LOG space (monotone-equivalent, immune to exp
    underflow) — the rule the scoring study showed rescues broad-response
    near-OoD (evidence/README.md "ood/"). "paper" (opt-in) scores BOTH
    sides with log p(x) — the quantity the paper's equations actually
    name — removing the reference implementation's C-fold sum-vs-mean
    asymmetry while keeping its scoring function; it is also the rule the
    serving calibration gates with (serving/calibration.py), so
    `evaluate --ood_score paper` reproduces serve-time abstention
    decisions exactly. `ood_thresh` is an exp-space density for "sum" and
    a log-density for "max"/"paper". The default stays "sum" (reference
    parity).
    """
    if score_rule not in ("sum", "max", "paper"):
        raise ValueError(
            f"score_rule must be 'sum', 'max' or 'paper', got {score_rule!r}"
        )
    id_log_px, correct, _, _, id_logits = _run_eval(trainer, state, id_batches)
    acc = float(correct.mean()) if correct.size else 0.0
    log(f"\tTest Acc: \t{acc * 100}")

    num_classes = state.gmm.num_classes
    # scores kept in float64 on host for a stable percentile. The sum rule
    # works in exp space for reference parity; the max rule has no parity
    # constraint and stays in LOG space — exp would underflow to 0.0 below
    # log-likelihood ~-745 (easy for high-dim GMMs), collapsing the
    # threshold to 0.0 and faking a perfect FPR
    if score_rule == "sum":
        id_score = np.exp(id_log_px.astype(np.float64))
    elif score_rule == "paper":
        id_score = id_log_px.astype(np.float64)  # log p(x), both sides
    else:
        id_score = id_logits.max(-1)
    ood_thresh = float(np.percentile(id_score, percentile))

    results: Dict[str, float] = {
        "acc": acc, "ood_thresh": ood_thresh, "score_rule": score_rule
    }
    for i, ood_batches in enumerate(ood_batch_iters, start=1):
        ood_log_px, _, _, _, ood_logits = _run_eval(trainer, state, ood_batches)
        if score_rule == "sum":
            # inherited asymmetry: threshold from SUM, OoD tested on MEAN
            # (reference train_and_test.py:196-213) — kept for parity
            ood_score = np.exp(ood_log_px.astype(np.float64)) / num_classes
        elif score_rule == "paper":
            # symmetric: the SAME log p(x) statistic as the threshold
            ood_score = ood_log_px.astype(np.float64)
        else:
            ood_score = ood_logits.max(-1)  # log space, like the threshold
        fpr = float((ood_score > ood_thresh).mean()) if ood_score.size else 0.0
        results[f"FPR95_{i}"] = fpr
        log(f"\tFPR95_{i}: \t{fpr}")
        if ood_log_px.size:
            auroc = binary_auroc(id_log_px, ood_log_px)
            results[f"AUROC_{i}"] = auroc
            log(f"\tAUROC_{i}: \t{auroc}")
            results[f"score_variants_{i}"] = {
                k: round(v, 6)
                for k, v in ood_score_variants(id_logits, ood_logits).items()
            }
            log(f"\tscore_variants_{i}: \t{results[f'score_variants_{i}']}")
    return acc, results


def _logsumexp(x: np.ndarray, axis: int = -1) -> np.ndarray:
    m = x.max(axis=axis, keepdims=True)
    return (m + np.log(np.exp(x - m).sum(axis=axis, keepdims=True))).squeeze(
        axis
    )


def ood_score_variants(
    id_logits: np.ndarray,
    ood_logits: np.ndarray,
    temperatures: Sequence[float] = (0.5, 2.0, 5.0),
) -> Dict[str, float]:
    """AUROC of OoD scoring rules over class log-likelihood matrices [N, C].

    Beyond-parity experiment (VERDICT r3): the reference scores OoD by
    sum_c p(x|c) only (train_and_test.py:184-229). Near-OoD inputs can
    excite a BROAD low response across many classes that sums to an
    ID-looking total; alternatives measured head-to-head:

      sum      — log sum_c p(x|c) (the inherited rule, = log p(x) under
                 uniform class priors)
      max      — max_c log p(x|c): is the input strongly explained by ANY
                 single class?
      temp_T   — T * log sum_c exp(log p(x|c) / T): temperature-scaled
                 p(x); T<1 sharpens toward max, T>1 flattens toward mean
    """
    out: Dict[str, float] = {}

    def auroc_of(fn) -> float:
        return binary_auroc(fn(id_logits), fn(ood_logits))

    out["sum"] = auroc_of(lambda L: _logsumexp(L))
    out["max"] = auroc_of(lambda L: L.max(-1))
    for t in temperatures:
        out[f"temp_{t:g}"] = auroc_of(lambda L: t * _logsumexp(L / t))
    return out


