"""Interpretability metrics: consistency, stability, purity.

Reference: utils/interpretability.py. All three metrics share one primitive:
for each prototype of an image's ground-truth class, upsample its activation
map to pixel space, take a box of `half_size` around the argmax, and mark
which annotated bird parts fall inside (the "hit matrix").

  * consistency (interpretability.py:134-160): a prototype is consistent if
    some part is hit in >= `part_thresh` of the class's images (normalized by
    that part's visibility count). Score = % consistent prototypes.
  * stability (interpretability.py:163-178): % of images whose hit vector is
    unchanged when imperceptible Gaussian noise perturbs the input.
  * purity (interpretability.py:183-315): over each prototype's top-K most
    activated images, the best per-part mean hit rate; score = mean/std over
    prototypes (x100).

Device work (forward + gt-class map gather) is one jitted function; the
geometric bookkeeping is host-side numpy exactly like the reference's CPU
post-pass. Activations are exp(log-density) = the reference's
`-proto_dist` (model.py:437) so bicubic upsampling (a non-monotone resample)
sees the same surface the reference feeds it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mgproto_tpu.core.mgproto import GMMState, patch_log_densities
from mgproto_tpu.data.cub_parts import CubParts, in_bbox
from mgproto_tpu.utils.vis import upsample_activation


def perturb_images(
    images: np.ndarray, rng: np.random.Generator, std: float = 0.2,
    eps: float = 0.25,
) -> np.ndarray:
    """Clipped Gaussian noise on NORMALIZED images (reference
    interpretability.py:14-18)."""
    noise = np.clip(
        rng.normal(0.0, std, size=images.shape), -eps, eps
    ).astype(images.dtype)
    return images + noise


def make_gt_act_fn(model):
    """Jitted: (params, batch_stats, gmm, images, labels) ->
    [B, K, H, W] exp-density maps of each image's gt-class prototypes
    (reference interpretability.py:49-56 gather)."""

    def fn(params, batch_stats, gmm: GMMState, images, labels):
        variables = {"params": params["net"], "batch_stats": batch_stats}
        proto_map, _ = model.apply(variables, images, train=False)
        log_prob, _ = patch_log_densities(proto_map, gmm)  # [B,C,K,H,W]
        sel = labels[:, None, None, None, None]
        lp = jnp.take_along_axis(log_prob, sel, axis=1)[:, 0]  # [B,K,H,W]
        return jnp.exp(lp)

    return jax.jit(fn)


def collect_gt_activations(
    trainer,
    state,
    batches,
    use_noise: bool = False,
    noise_seed: int = 0,
    act_fn=None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Run the test set; returns (acts [N,K,h,w], targets [N], img_ids [N]).
    `batches` yields (normalized images, labels, img_ids); padded tail rows
    (label -1) are dropped. Pass a prebuilt `act_fn` (make_gt_act_fn) to
    share one compiled forward across metric passes."""
    if act_fn is None:
        act_fn = make_gt_act_fn(trainer.model)
    # per-process local jit over this process's loader shard; results are
    # gathered globally below (parallel/multihost.py)
    from mgproto_tpu.parallel.multihost import allgather_rows, fetch_replicated

    params_h, stats_h, gmm_h = fetch_replicated(
        (state.params, state.batch_stats, state.gmm),
        getattr(trainer, "mesh", None),
    )
    rng = np.random.default_rng(noise_seed)
    accs, targets, ids, valids = [], [], [], []
    for images, labels, img_ids in batches:
        images = np.asarray(images, np.float32)
        if use_noise:
            images = perturb_images(images, rng)
        acts = act_fn(
            params_h,
            stats_h,
            gmm_h,
            jnp.asarray(images),
            jnp.asarray(np.maximum(labels, 0), jnp.int32),
        )
        accs.append(np.asarray(jax.device_get(acts)))
        targets.append(np.asarray(labels))
        ids.append(np.asarray(img_ids))
        valids.append(np.asarray(labels) >= 0)
    acc = allgather_rows(np.concatenate(accs))
    target = allgather_rows(np.concatenate(targets))
    img_id = allgather_rows(np.concatenate(ids))
    valid = allgather_rows(np.concatenate(valids)).astype(bool)
    return acc[valid], target[valid], img_id[valid]


def peak_box(
    act_map: np.ndarray, img_size: int, half_size: int
) -> Tuple[int, int, int, int]:
    """(y1, y2, x1, x2) box of side 2*half_size around the upsampled
    activation argmax, clipped to the image (reference
    interpretability.py:108-120 region arithmetic)."""
    up = upsample_activation(act_map, (img_size, img_size))
    my, mx = np.unravel_index(np.argmax(up), up.shape)
    return (
        max(0, int(my) - half_size),
        min(img_size, int(my) + half_size),
        max(0, int(mx) - half_size),
        min(img_size, int(mx) + half_size),
    )


def hit_matrix(
    act_maps: np.ndarray,  # [N, K, h, w] one class's images
    part_labels: Sequence[Sequence[Sequence[int]]],  # per image [(pid, x, y)]
    part_num: int,
    img_size: int,
    half_size: int,
    rows: Optional[Sequence[Tuple[int, int]]] = None,  # (out_row, img_idx) per K
) -> np.ndarray:
    """The shared geometric core (reference interpretability.py:108-131):
    for image i and prototype k, mark parts within `half_size` of the
    upsampled activation argmax. Returns [K, R, part_num] where R = number of
    rows (= N, or len(rows) when a top-K subset is scored)."""
    n, k_per_class = act_maps.shape[:2]
    r = n if rows is None else len(rows)
    out = np.zeros((k_per_class, r, part_num))
    for k in range(k_per_class):
        row_iter = (
            enumerate(range(n)) if rows is None else enumerate(rows)
        )
        for out_row, img_idx in row_iter:
            region = peak_box(act_maps[img_idx, k], img_size, half_size)
            for pid, x, y in part_labels[img_idx]:
                if in_bbox((y, x), region):
                    out[k, out_row, pid] = 1
    return out


def _per_class_annotations(
    parts: CubParts, img_ids: np.ndarray, img_size: int
) -> Tuple[List[List[List[int]]], np.ndarray]:
    """Part labels + visibility masks for a class's images, rescaled to the
    model's input size using each image's ORIGINAL dimensions."""
    labels, masks = [], []
    for img_id in img_ids:
        pl, mask = parts.scaled_part_labels(
            int(img_id), parts.orig_wh(int(img_id)), img_size
        )
        labels.append(pl)
        masks.append(mask)
    return labels, np.stack(masks)


def _topk_rows(class_acts: np.ndarray, top_k: int) -> np.ndarray:
    """[kk, K] image rows of each prototype's top-K peak activations —
    the ONE selection rule shared by evaluate_purity and the CSV export
    (stable sort: ties break toward the earlier image)."""
    peak = class_acts.max(axis=(2, 3))  # [N, K]
    order = np.argsort(-peak, axis=0, kind="stable")
    return order[: min(top_k, class_acts.shape[0])]


def _iter_class_hits(
    acts: np.ndarray,
    targets: np.ndarray,
    img_ids: np.ndarray,
    parts: CubParts,
    img_size: int,
    half_size: int,
    num_classes: int,
    top_k: Optional[int] = None,
):
    """Yields (class, hits [K,R,P], masks [N,P]) per class, in class order.
    With top_k, R indexes each prototype's top-K most-activated images
    (reference interpretability.py:222-224)."""
    for c in range(num_classes):
        idx = np.nonzero(targets == c)[0]
        if idx.size == 0:
            continue
        class_acts = acts[idx]
        labels, masks = _per_class_annotations(parts, img_ids[idx], img_size)
        if top_k is None:
            yield c, hit_matrix(
                class_acts, labels, parts.part_num, img_size, half_size
            ), masks
        else:
            order = _topk_rows(class_acts, top_k)
            # one single-prototype hit_matrix per k: scoring only that
            # prototype's top-K images (not K x K work)
            hits = np.stack(
                [
                    hit_matrix(
                        class_acts[:, k : k + 1],
                        labels,
                        parts.part_num,
                        img_size,
                        half_size,
                        rows=list(order[:, k]),
                    )[0]
                    for k in range(class_acts.shape[1])
                ]
            )
            yield c, hits, masks


def evaluate_consistency(
    trainer,
    state,
    batches,
    parts: CubParts,
    num_classes: int,
    half_size: int = 36,
    part_thresh: float = 0.8,
    activations: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> float:
    """% of prototypes hitting the same visible part in >= part_thresh of
    their class's images (reference interpretability.py:134-160).
    `activations` = a precomputed collect_gt_activations triple (shared
    across metrics so the test set forwards once)."""
    img_size = trainer.cfg.model.img_size
    acts, targets, img_ids = (
        activations
        if activations is not None
        else collect_gt_activations(trainer, state, batches)
    )
    consis = []
    for _c, hits, masks in _iter_class_hits(
        acts, targets, img_ids, parts, img_size, half_size, num_classes
    ):
        vis_count = np.maximum(masks.sum(axis=0), 1.0)  # [P]
        for k in range(hits.shape[0]):
            mean_part = hits[k].sum(axis=0) / vis_count
            consis.append(1 if (mean_part >= part_thresh).any() else 0)
    return float(np.mean(consis) * 100.0)


def evaluate_stability(
    trainer,
    state,
    batches_factory,
    parts: CubParts,
    num_classes: int,
    half_size: int = 36,
    noise_seed: int = 0,
    activations: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    act_fn=None,
) -> float:
    """% of (prototype, image) hit vectors unchanged under input noise
    (reference interpretability.py:163-178). `batches_factory()` returns a
    fresh batch iterator (the noisy pass always re-reads it; the clean pass
    reuses `activations` when given)."""
    img_size = trainer.cfg.model.img_size
    if act_fn is None:
        act_fn = make_gt_act_fn(trainer.model)
    acts, targets, img_ids = (
        activations
        if activations is not None
        else collect_gt_activations(
            trainer, state, batches_factory(), act_fn=act_fn
        )
    )
    acts_n, _, _ = collect_gt_activations(
        trainer,
        state,
        batches_factory(),
        use_noise=True,
        noise_seed=noise_seed,
        act_fn=act_fn,
    )
    stab = []
    clean = _iter_class_hits(
        acts, targets, img_ids, parts, img_size, half_size, num_classes
    )
    noisy = _iter_class_hits(
        acts_n, targets, img_ids, parts, img_size, half_size, num_classes
    )
    for (_c, h0, _m0), (_c2, h1, _m1) in zip(clean, noisy):
        for k in range(h0.shape[0]):
            unchanged = (np.abs(h0[k] - h1[k]).sum(axis=-1) == 0)
            stab.append(unchanged.mean())
    return float(np.mean(stab) * 100.0)


def evaluate_purity(
    trainer,
    state,
    batches,
    parts: CubParts,
    num_classes: int,
    half_size: int = 16,
    top_k: int = 10,
    activations: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> Tuple[float, float]:
    """Mean/std over prototypes of the best per-part hit rate across each
    prototype's top-K activated images (reference interpretability.py:298-315)."""
    img_size = trainer.cfg.model.img_size
    acts, targets, img_ids = (
        activations
        if activations is not None
        else collect_gt_activations(trainer, state, batches)
    )
    purity = []
    for _c, hits, _masks in _iter_class_hits(
        acts, targets, img_ids, parts, img_size, half_size, num_classes,
        top_k=top_k,
    ):
        for k in range(hits.shape[0]):
            purity.append(hits[k].mean(axis=0).max())
    arr = np.asarray(purity)
    return float(arr.mean() * 100.0), float(arr.std() * 100.0)


# ------------------------------------------------------- CSV export (parity)
def export_prototype_patches_csv(
    path: str,
    trainer,
    state,
    batches,
    num_classes: int,
    half_size: int = 16,
    top_k: int = 10,
    activations: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> int:
    """Write each prototype's top-K activated patches as CSV rows
    `class,k,rank,img_id,ymin,ymax,xmin,xmax` (coordinates on the model's
    input grid) — the reference's method-agnostic purity interchange format
    (reference cub_csv.py:225-266 `get_proto_patches_cub` /
    eval_prototypes_cub_parts_csv input). Returns the number of rows."""
    import csv as _csv

    img_size = trainer.cfg.model.img_size
    acts, targets, img_ids = (
        activations
        if activations is not None
        else collect_gt_activations(trainer, state, batches)
    )
    rows = 0
    with open(path, "w", newline="") as f:
        w = _csv.writer(f)
        w.writerow(
            ["class", "k", "rank", "img_id", "ymin", "ymax", "xmin", "xmax"]
        )
        for c in range(num_classes):
            idx = np.nonzero(targets == c)[0]
            if idx.size == 0:
                continue
            class_acts = acts[idx]
            class_ids = img_ids[idx]
            order = _topk_rows(class_acts, top_k)
            for k in range(class_acts.shape[1]):
                for rank, n in enumerate(order[:, k]):
                    y1, y2, x1, x2 = peak_box(
                        class_acts[n, k], img_size, half_size
                    )
                    w.writerow(
                        [c, k, rank, int(class_ids[n]), y1, y2, x1, x2]
                    )
                    rows += 1
    return rows


def purity_from_csv(
    csvfile: str, parts: CubParts, img_size: int
) -> Tuple[float, float]:
    """Recompute purity from an exported patch CSV — works for ANY
    part-prototype method that emits the same rows (reference
    cub_csv.py:55-222 `eval_prototypes_cub_parts_csv` capability). Must agree
    with `evaluate_purity` when fed this framework's own export."""
    import csv as _csv
    from collections import defaultdict

    by_proto = defaultdict(list)
    with open(csvfile, newline="") as f:
        reader = _csv.DictReader(f)
        for row in reader:
            by_proto[(int(row["class"]), int(row["k"]))].append(
                (
                    int(row["img_id"]),
                    (
                        int(row["ymin"]),
                        int(row["ymax"]),
                        int(row["xmin"]),
                        int(row["xmax"]),
                    ),
                )
            )
    purity = []
    for (_c, _k), entries in sorted(by_proto.items()):
        hits = np.zeros((len(entries), parts.part_num))
        for r, (img_id, box) in enumerate(entries):
            labels, _ = parts.scaled_part_labels(
                img_id, parts.orig_wh(img_id), img_size
            )
            for pid, x, y in labels:
                if in_bbox((y, x), box):
                    hits[r, pid] = 1
        purity.append(hits.mean(axis=0).max())
    arr = np.asarray(purity)
    return float(arr.mean() * 100.0), float(arr.std() * 100.0)
