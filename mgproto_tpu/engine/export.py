"""Serialized inference artifacts via jax.export (StableHLO).

Beyond the reference: its deployment story is a torch `state_dict` that needs
the full Python model code (and its exact class layout) to run again
(reference eval_purity.py:55 restores with `load_state_dict(strict=False)`).
A TPU-native artifact should instead be the COMPILED PROGRAM: here the eval
step — backbone, density scoring, mixture head, log p(x) OoD score — is
staged out with `jax.export` into one self-contained StableHLO module with
the weights baked in as constants and a symbolic batch dimension. The result
runs with `jax.export.deserialize(...).call(images)` alone: no mgproto_tpu
import, no checkpoint plumbing, no Python model definition, any XLA backend.

The exported program always uses the portable XLA scoring path (a serialized
`pallas_call` would pin the artifact to TPU and to a Mosaic version); the
fused kernel is a training-time optimization, and the two paths are
numerically identical (tests/test_fused_scoring.py).

Artifact layout: a single zip (conventionally `*.mgproto`) holding
  model.stablehlo  — jax.export serialization (weights inlined)
  meta.json        — model/provenance metadata (arch, classes, shapes,
                     dtype, gmm_fingerprint)
  calibration.json — optional ID-score calibration (serving/calibration.py):
                     log p(x) percentile thresholds + quantile sketch +
                     per-class temperatures, stamped with the fingerprint
                     of the GMM they were measured under. The serving
                     engine refuses to trust-gate without it.

Int8 weight-only artifacts (ISSUE 20, perf/quant.py): with
`mgproto-export --quantize int8` the MAIN program's baked trunk constants
are int8 kernels + per-output-channel f32 scales, dequantized in-kernel
behind `lax.optimization_barrier` (without the barrier XLA constant-folds
the dequant at compile time and bakes the f32 tensors right back — 4-byte
weight traffic restored, silently). meta.json then carries a
`quant_config` block (mode, tag, byte accounting, content fingerprint),
and a second staged program — `dequant.stablehlo`, the same dequantized
weights exported as plain f32 constants — rides along as the debug/parity
reference reachable via `load_artifact(dequantize=True)`. `--quantize
none` writes today's artifact byte-identically: no extra blob, no
`quant_config` key, nothing for old loaders to trip on.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zipfile
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export

from mgproto_tpu.engine.train import Trainer

_BLOB_NAME = "model.stablehlo"
_META_NAME = "meta.json"
_CALIB_NAME = "calibration.json"
# opt-in explanation sidecars (ISSUE 15): a second staged program with
# superset outputs (top activated prototypes per request) + the static
# prototype table (priors, push provenance) the serving engine attaches
# to predict outcomes. The PLAIN program stays untouched, so serving
# without --explain pays nothing for an artifact that carries these.
_EXPLAIN_BLOB = "explain.stablehlo"
_EXPLAIN_TABLE = "explain.json"
# opt-in int8 debug sidecar (ISSUE 20): the dequantize-to-f32 twin of a
# quantized main program (same rounded weight VALUES, plain f32 constants)
_DEQUANT_BLOB = "dequant.stablehlo"


def export_eval(trainer, state, dynamic_batch: bool = True,
                static_batch: int = 8,
                platforms: Tuple[str, ...] = ("cpu", "tpu", "cuda"),
                quantized=None):
    """Stage the eval step out as a jax.export.Exported.

    The returned program maps f32 images [b, H, W, 3] (already normalized,
    exactly what `Trainer.eval_step` takes) to
    {"logits": [b, C] class log-likelihoods, "log_px": [b] OoD score}.
    `dynamic_batch=True` exports a symbolic batch dimension so one artifact
    serves any batch size; False pins `static_batch` (some non-XLA consumers
    of StableHLO cannot handle symbolic dims). `platforms` defaults to a
    multi-platform lowering — without it jax.export pins the artifact to the
    EXPORTING machine's backend, so a TPU-side export could not serve on a
    CPU host (the exact portability this feature promises).

    `quantized` (a perf/quant.py QuantizedParams) swaps the trunk params
    for their int8 + per-channel-scale form, dequantized INSIDE the traced
    program behind an optimization barrier: the staged constants are the
    1-byte tensors, the dequant multiply fuses into the consuming conv
    read at serve time. The GMM head / log p(x) path is untouched — it
    reads state.gmm, which quantization never sees."""
    cfg = trainer.cfg
    if trainer._fused:
        # re-resolve on a plain Trainer with the portable path forced; the
        # SAME cfg/state produce identical numerics on the XLA path
        portable = cfg.replace(
            model=dataclasses.replace(cfg.model, fused_scoring=False)
        )
        trainer = Trainer(portable, steps_per_epoch=1)

    def infer(images):
        eval_state = state
        if quantized is not None:
            # materialize inside the trace so the barrier keeps the int8
            # constants live in the exported module
            eval_state = state.replace(
                params=quantized.materialize(barrier=True)
            )
        out = trainer._eval(eval_state, images, None)
        return {"logits": out.logits, "log_px": out.log_px}

    if dynamic_batch:
        (b,) = jax_export.symbolic_shape("b")
    else:
        b = static_batch
    spec = jax.ShapeDtypeStruct(
        (b, cfg.model.img_size, cfg.model.img_size, 3), jnp.float32
    )
    return jax_export.export(jax.jit(infer), platforms=list(platforms))(spec)


def save_artifact(path: str, exported, meta: Dict[str, Any],
                  calibration=None, explain=None, dequant=None) -> None:
    """One-file artifact: the serialized program + meta.json (+ the
    serving calibration when given — a `serving.calibration.Calibration`
    or an already-serialized dict; + the explain sidecars when given — an
    (exported_explain_program, table_dict) pair from `export_explain` /
    `explain_table`; + the dequantize-to-f32 debug program when given —
    the quantized export's parity reference, `load_artifact(
    dequantize=True)`)."""
    with zipfile.ZipFile(path, "w", compression=zipfile.ZIP_DEFLATED) as z:
        z.writestr(_BLOB_NAME, bytes(exported.serialize()))
        z.writestr(_META_NAME, json.dumps(meta, indent=2, sort_keys=True))
        if calibration is not None:
            z.writestr(_CALIB_NAME, _calib_json(calibration))
        if explain is not None:
            explain_exported, table = explain
            z.writestr(_EXPLAIN_BLOB, bytes(explain_exported.serialize()))
            z.writestr(
                _EXPLAIN_TABLE,
                json.dumps(table, indent=2, sort_keys=True),
            )
        if dequant is not None:
            z.writestr(_DEQUANT_BLOB, bytes(dequant.serialize()))


def _calib_json(calibration) -> str:
    if isinstance(calibration, dict):
        return json.dumps(calibration, indent=2, sort_keys=True)
    return calibration.to_json()


def embed_calibration(path: str, calibration) -> None:
    """Add (or replace) the calibration inside an existing artifact —
    recalibration after a prune/EM touch-up must not require re-staging
    the StableHLO program. Rewrites the zip atomically."""
    tmp = path + ".tmp"
    with zipfile.ZipFile(path) as src:
        entries = [n for n in src.namelist() if n != _CALIB_NAME]
        with zipfile.ZipFile(
            tmp, "w", compression=zipfile.ZIP_DEFLATED
        ) as dst:
            for name in entries:
                dst.writestr(name, src.read(name))
            dst.writestr(_CALIB_NAME, _calib_json(calibration))
    os.replace(tmp, path)


def load_calibration(path: str):
    """The artifact's embedded `serving.calibration.Calibration`, or None
    when it carries no calibration. (Unlike `load_artifact`, this pulls in
    `mgproto_tpu.serving.calibration` — numpy + stdlib only, still safe on
    a bare serving host.)"""
    from mgproto_tpu.serving.calibration import Calibration

    with zipfile.ZipFile(path) as z:
        if _CALIB_NAME not in z.namelist():
            return None
        return Calibration.from_json(z.read(_CALIB_NAME).decode())


def make_explain_fn(trainer, state, top_e: int = 5):
    """The explain inference function: images -> {"logits", "log_px",
    "proto_idx" [B, E] flat C*K prototype indices, "proto_logd" [B, E]
    peak patch log-densities}, most activated first. logits/log_px take
    the portable XLA head path — numerically identical to the fused
    kernel (tests/test_fused_scoring.py), and an explain program must
    export/serve everywhere the plain one does.

    Pruned prototypes (prior exactly 0, `core/mgproto.py::prune_top_m`)
    are masked to -inf before the top-k: a dead mixture component must
    never headline an explanation."""
    import numpy as np

    from mgproto_tpu.core.mgproto import (
        head_forward,
        log_px as _log_px,
        patch_log_densities,
    )

    cfg = trainer.cfg
    c, k = state.gmm.priors.shape
    top_e = int(min(top_e, c * k))

    def infer(images):
        (proto_map, _), _ = trainer._apply(
            state.params, state.batch_stats, images, train=False
        )
        logits, _, _ = head_forward(
            proto_map, state.gmm, None, cfg.model.mine_T, fused=False
        )
        lvl0 = logits[..., 0]
        lp, _ = patch_log_densities(proto_map, state.gmm)  # [B,C,K,H,W]
        b = lp.shape[0]
        peak = jnp.max(lp, axis=(3, 4)).reshape(b, c * k)
        live = (state.gmm.priors > 0).reshape(c * k)
        masked = jnp.where(live[None, :], peak, -jnp.inf)
        logd, idx = jax.lax.top_k(masked, top_e)
        return {
            "logits": lvl0,
            "log_px": _log_px(lvl0),
            "proto_idx": idx.astype(np.int32),
            "proto_logd": logd,
        }

    return infer


def explain_table(state, provenance: Optional[Dict[str, Any]] = None,
                  ) -> Dict[str, Any]:
    """The static prototype table the serving engine resolves explanation
    rows against: flat-indexed priors + optional push provenance
    (engine/push.py::provenance_dict — nearest training patch per
    prototype). JSON-able; persisted as explain.json inside the artifact
    so an exported model explains itself with no training run around."""
    import numpy as np

    c, k = state.gmm.priors.shape
    table: Dict[str, Any] = {
        "format": "mgproto-explain-v1",
        "num_classes": int(c),
        "k_per_class": int(k),
        "priors": [
            round(float(v), 8)
            for v in np.asarray(state.gmm.priors).reshape(-1)
        ],
        "provenance": None,
    }
    if provenance is not None:
        for key in ("image_id", "spatial_idx", "log_prob"):
            if key not in provenance:
                raise ValueError(
                    f"provenance dict missing {key!r} (expected the "
                    "engine/push.py::provenance_dict shape)"
                )
        table["provenance"] = {
            "image_id": [int(v) for v in
                         np.asarray(provenance["image_id"]).reshape(-1)],
            "spatial_idx": [int(v) for v in
                            np.asarray(provenance["spatial_idx"]).reshape(-1)],
            "log_prob": [round(float(v), 6) for v in
                         np.asarray(provenance["log_prob"]).reshape(-1)],
        }
    return table


def export_explain(trainer, state, top_e: int = 5,
                   dynamic_batch: bool = True, static_batch: int = 8,
                   platforms: Tuple[str, ...] = ("cpu", "tpu", "cuda")):
    """Stage the explain program out as a jax.export.Exported (the
    `export_eval` of the explanation path; same batch-dimension and
    multi-platform rules)."""
    cfg = trainer.cfg
    if trainer._fused:
        portable = cfg.replace(
            model=dataclasses.replace(cfg.model, fused_scoring=False)
        )
        trainer = Trainer(portable, steps_per_epoch=1)
    infer = make_explain_fn(trainer, state, top_e=top_e)
    if dynamic_batch:
        (b,) = jax_export.symbolic_shape("b")
    else:
        b = static_batch
    spec = jax.ShapeDtypeStruct(
        (b, cfg.model.img_size, cfg.model.img_size, 3), jnp.float32
    )
    return jax_export.export(jax.jit(infer), platforms=list(platforms))(spec)


def load_explain(path: str) -> Tuple[Any, Optional[Dict[str, Any]]]:
    """(explain Exported | None, table | None) from an artifact. Both are
    None for artifacts exported without --explain."""
    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        if _EXPLAIN_BLOB not in names or _EXPLAIN_TABLE not in names:
            return None, None
        exported = jax_export.deserialize(z.read(_EXPLAIN_BLOB))
        table = json.loads(z.read(_EXPLAIN_TABLE))
    return exported, table


def load_exported(path: str) -> Tuple[Any, Dict[str, Any]]:
    """(jax.export.Exported, meta) — the full deserialized program object,
    for callers that need its input avals (e.g. recovering the pinned
    batch size of a static export whose meta predates `static_batch`)."""
    with zipfile.ZipFile(path) as z:
        exported = jax_export.deserialize(z.read(_BLOB_NAME))
        meta = json.loads(z.read(_META_NAME))
    return exported, meta


def artifact_head_fingerprint(path: str) -> str:
    """The artifact's HEAD identity (ISSUE 17): sha256 over its embedded
    calibration payload — the half of the serving identity that is
    per-tenant. The trunk half is `artifact_aot_fingerprint` below; the
    split is what lets N tenants share one compiled trunk in the AOT cache
    while each mounts its own head. "" when the artifact carries no
    calibration (a head that only serves degraded)."""
    from mgproto_tpu.serving.tenants import head_fingerprint

    return head_fingerprint(load_calibration(path))


def artifact_aot_fingerprint(path: str) -> str:
    """The artifact face's AOT-cache program fingerprint: sha256 of the
    `.mgproto` file + the mixture fingerprint from its meta. The ONE
    formula `export_aot_cache`, `ServingEngine.from_artifact` and the
    serve CLI share — any re-export changes the file hash, so stale
    executables miss instead of serving."""
    from mgproto_tpu.serving.aotcache import file_fingerprint

    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read(_META_NAME))
    return file_fingerprint(path) + ":" + (meta.get("gmm_fingerprint") or "")


def quant_tag(meta: Dict[str, Any]) -> str:
    """The serving-seam quant identity of an artifact's meta block
    (perf/quant.py quant_config "tag"; "" for unquantized / pre-quant
    artifacts). The ONE derivation `ServingEngine.from_artifact`,
    `export_aot_cache` and the serve CLI share."""
    return str((meta.get("quant_config") or {}).get("tag") or "")


def export_aot_cache(
    path: str,
    buckets: Sequence[int] = (1, 2, 4, 8),
    cache_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Prebuild the AOT executable cache for an exported artifact: compile
    the artifact's program at every serving bucket on THIS machine and
    serialize each executable into the sidecar cache (serving/aotcache.py;
    default `<path>.aotcache/`). A replica starting on hardware matching
    this machine's (device kind, topology, jax/jaxlib) then warms every
    bucket with ZERO compiles — the mmap-and-go cold start. Other hardware
    simply misses (the key carries the environment) and compiles normally,
    lazily repopulating its own entries.

    Returns a summary dict: per-bucket store outcome + the cache key's
    environment half (`mgproto-export --aot-cache` prints it)."""
    from mgproto_tpu.serving.aotcache import (
        ExecutableCache,
        default_cache_dir,
        environment_fingerprint,
    )

    exported, meta = load_exported(path)
    cache = ExecutableCache(cache_dir or default_cache_dir(path))
    fingerprint = artifact_aot_fingerprint(path)
    policy = meta.get("precision_policy") or {}
    dtype = policy.get("compute_dtype") or meta.get("compute_dtype") or ""
    img = int(meta["img_size"])
    if not meta.get("dynamic_batch", True):
        static = meta.get("static_batch") or int(
            exported.in_avals[0].shape[0]
        )
        buckets = (int(static),)
    quant = quant_tag(meta)
    jit_call = jax.jit(exported.call)
    stored: Dict[str, bool] = {}
    for b in sorted(set(int(x) for x in buckets)):
        spec = jax.ShapeDtypeStruct((b, img, img, 3), jnp.float32)
        compiled = jit_call.lower(spec).compile()
        key = cache.key(fingerprint, (b, img, img, 3), dtype, quant=quant)
        stored[f"b{b}"] = cache.store(key, compiled)
    return {
        "cache_dir": cache.cache_dir,
        "program_fingerprint": fingerprint,
        "compute_dtype": dtype,
        "quant": quant,
        "stored": stored,
        "environment": environment_fingerprint(),
    }


def load_artifact(
    path: str, dequantize: bool = False
) -> Tuple[Callable, Dict[str, Any]]:
    """(callable, meta): the callable maps images -> {"logits", "log_px"}.

    Needs only jax — deliberately no mgproto_tpu imports in the load path
    (the artifact must stay loadable from a bare serving environment; this
    helper is a convenience over `jax.export.deserialize`).

    `dequantize=True` loads the quantized artifact's dequantize-to-f32
    DEBUG program (`dequant.stablehlo`: the same rounded weight values as
    plain f32 constants — for pinning int8-serving outputs against an
    all-f32 execution, tests/test_quant.py). On an unquantized artifact
    the flag is a documented no-op: there is only one program and it IS
    the f32 one."""
    if dequantize:
        with zipfile.ZipFile(path) as z:
            names = z.namelist()
            meta = json.loads(z.read(_META_NAME))
            blob = (
                _DEQUANT_BLOB if _DEQUANT_BLOB in names else _BLOB_NAME
            )
            exported = jax_export.deserialize(z.read(blob))
        return exported.call, meta
    exported, meta = load_exported(path)
    return exported.call, meta


def artifact_meta(cfg, checkpoint_path: Optional[str],
                  dynamic_batch: bool,
                  gmm_fingerprint: Optional[str] = None,
                  static_batch: Optional[int] = None,
                  quant: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Provenance block written next to the program. `gmm_fingerprint`
    identifies the mixture the weights carry (serving/calibration.py) —
    the serving gate matches it against the embedded calibration's stamp
    and fails closed on disagreement. `quant` is a QuantizedParams
    .quant_config() block; when None (the f32 path) the `quant_config`
    key is NOT written at all, keeping `--quantize none` byte-identical
    to a pre-quant export."""
    from mgproto_tpu.perf.precision import policy_meta, resolve_policy

    meta: Dict[str, Any] = {
        "gmm_fingerprint": gmm_fingerprint,
        "static_batch": None if dynamic_batch else static_batch,
        "format": "mgproto-stablehlo-v1",
        "arch": cfg.model.arch,
        "num_classes": cfg.model.num_classes,
        "prototypes_per_class": cfg.model.prototypes_per_class,
        "proto_dim": cfg.model.proto_dim,
        "img_size": cfg.model.img_size,
        "compute_dtype": cfg.model.compute_dtype,
        # the full precision policy (perf/precision.py): what the exported
        # program computes in, and the f32 invariants it was trained under.
        # The serving TrustGate matches the calibration's dtype stamp
        # against this and fails closed on disagreement.
        "precision_policy": policy_meta(resolve_policy(cfg)),
        "input": "float32 [batch, img_size, img_size, 3], normalized",
        "outputs": {
            "logits": "[batch, num_classes] class log-likelihoods log p(x|c)",
            "log_px": "[batch] generative OoD score log p(x)",
        },
        "dynamic_batch": dynamic_batch,
        "checkpoint": checkpoint_path,
        "jax_version": jax.__version__,
    }
    if quant is not None:
        meta["quant_config"] = quant
    return meta
