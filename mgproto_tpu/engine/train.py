"""Jitted train/eval steps + epoch drivers.

Reference: train_and_test.py. One fused, jitted step does what the reference
spreads over forward / backward / optimizer / memory enqueue / EM call
(train_and_test.py:26-64): the EM update runs INSIDE the step under lax.cond
(reference calls model.module.update_GMM() every iteration once gated —
bypassing DataParallel; here it's just more of the same jitted program, so it
shards with the rest).

Dynamic gates (`use_mine`, `update_gmm`) are traced scalars, not python
bools — flipping them mid-training does not retrigger compilation. The
warm/joint phase IS a static switch (two optimizers with different
topologies, reference main.py:205-220), giving two compiled variants.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mgproto_tpu.config import Config
from mgproto_tpu.core import losses as L
from mgproto_tpu.core.em import em_update, make_mean_optimizer, resolve_em_config
from mgproto_tpu.core.memory import memory_push
from mgproto_tpu.core.mgproto import (
    MGProtoFeatures,
    head_forward,
    log_px,
)
from mgproto_tpu.core.state import (
    TrainState,
    create_train_state,
    make_joint_optimizer,
    make_warm_optimizer,
)
from mgproto_tpu.ops.augment import augment_tail, resolve_device_augment


class TrainMetrics(NamedTuple):
    loss: jax.Array
    cross_entropy: jax.Array
    mine: jax.Array
    aux: jax.Array
    accuracy: jax.Array
    full_mem_ratio: jax.Array  # fraction of classes with a full queue
    em_active: jax.Array  # classes EM touched this step
    # EM calls that exceeded the compact width and took the dense lax.cond
    # fallback (core/em.py; per-step 0/1, epoch SUM after train_epoch)
    em_compact_fallback: jax.Array
    nonfinite: jax.Array  # bool: this step's update was SKIPPED (bad loss/grads)


class EvalOutput(NamedTuple):
    logits: jax.Array  # [B, C] level-0 class log-likelihoods
    log_px: jax.Array  # [B] log p(x) OoD score
    correct: jax.Array  # [B] bool (vs labels if given, else False)


class Trainer:
    """Owns the model + optimizers (static) and the jitted step functions.

    All state flows through `TrainState`; nothing here mutates."""

    def __init__(self, cfg: Config, steps_per_epoch: int, donate: bool = False):
        self.cfg = cfg
        self.steps_per_epoch = steps_per_epoch
        self.donate = donate
        self.model = MGProtoFeatures(cfg=cfg.model)
        # fused_scoring=None resolves per backend: the Pallas kernel measured
        # 1.9x faster than the XLA path on real TPU (BENCH_PROBE_RUN.json)
        # so TPU defaults to it; CPU/GPU fall back to the XLA path (the
        # interpret-mode kernel is correct but slow). On class-sharded meshes
        # ShardedTrainer keeps the kernel via shard_map (_score_mesh below),
        # dropping to the XLA path only when num_classes cannot shard over
        # the model axis. Explicit True/False is always honored.
        self._fused = self._resolve_fused(cfg.model.fused_scoring)
        # set by ShardedTrainer when the class axis is sharded: head_forward
        # then shard_maps the Pallas kernel over this mesh (core/mgproto.py)
        self._score_mesh = None
        # uint8 wire format + device augmentation tail (ops/augment.py):
        # flip + b/c/s jitter + normalize run inside the jitted step on the
        # u8 batch, per-sample seeded. Resolved like fused_scoring (auto =
        # TPU); a static python bool, so the traced program has no augment
        # code at all when off.
        self._device_augment = resolve_device_augment(cfg.data.device_augment)
        self.joint_tx = make_joint_optimizer(cfg, steps_per_epoch)
        self.warm_tx = make_warm_optimizer(cfg)
        self.proto_tx = make_mean_optimizer(cfg.em)
        # compact dirty-class EM: auto width resolves to the GLOBAL batch
        # (one step can newly dirty at most one class per batch row), so the
        # dense fallback fires only when EM was gated off long enough for
        # dirt to accumulate (core/em.py resolve_em_config)
        self._em_cfg = resolve_em_config(
            cfg.em,
            cfg.model.num_classes,
            cfg.data.train_batch_size * jax.process_count(),
        )
        # donate=True reuses the incoming state's buffers (params + opt
        # moments + memory bank, ~300 MB at flagship scale) in place instead
        # of copying each step. The production drivers (cli.train, bench.py)
        # enable it and always rebind `state` to the returned one; it stays
        # off by default so interactive callers may re-step an old state.
        self._train_step = jax.jit(
            self._step,
            static_argnames=("warm",),
            donate_argnums=(0,) if donate else (),
        )
        self._eval_step = jax.jit(self._eval)
        # the live jit callables, for telemetry's recompile detection
        # (StepMonitor reads their _cache_size deltas). ShardedTrainer
        # rebinds this when it builds its sharded jits.
        self._jit_handles = [self._train_step, self._eval_step]

    @property
    def jit_handles(self):
        """Current jitted step callables (telemetry watches these for
        cache-miss/recompile growth)."""
        return list(self._jit_handles)

    def _resolve_fused(self, fused: Optional[bool]) -> bool:
        if fused is not None:
            return fused
        return jax.default_backend() == "tpu"

    def init_state(self, rng: jax.Array, for_restore: bool = False) -> TrainState:
        """`for_restore=True` builds a restore TARGET: skips the pretrained
        trunk load (every weight is about to be overwritten by the orbax
        restore, and eval hosts need not carry the torch .pth)."""
        state, _ = create_train_state(
            self.cfg,
            self.steps_per_epoch,
            rng,
            model=self.model,
            joint_tx=self.joint_tx,
            warm_tx=self.warm_tx,
            proto_tx=self.proto_tx,
            for_restore=for_restore,
        )
        return state

    # ------------------------------------------------------------------ train
    def _apply(
        self, params, batch_stats, images, train: bool
    ) -> Tuple[Tuple[jax.Array, jax.Array], Any]:
        variables = {"params": params["net"], "batch_stats": batch_stats}
        if train:
            (proto_map, embed), mut = self.model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            return (proto_map, embed), mut["batch_stats"]
        proto_map, embed = self.model.apply(variables, images, train=False)
        return (proto_map, embed), batch_stats

    def _loss_fn(
        self, params, state: TrainState, images, labels, use_mine: jax.Array
    ):
        (proto_map, embed), new_stats = self._apply(
            params, state.batch_stats, images, train=True
        )
        logits, pooled, enq = head_forward(
            proto_map, state.gmm, labels, self.cfg.model.mine_T,
            fused=self._fused, mesh=self._score_mesh,
        )
        ce = L.cross_entropy(logits[..., 0], labels)
        mine = L.mine_loss(logits, labels) * use_mine
        aux_fn = L.AUX_LOSSES[self.cfg.loss.aux_loss]
        if self.cfg.loss.aux_loss in L.PROXY_BASED:
            aux = aux_fn(embed, labels, params["proxies"])
        else:
            aux = aux_fn(embed, labels)
        c = self.cfg.loss
        loss = c.crs_ent * ce + c.mine * mine + c.aux * aux
        acc = jnp.mean(jnp.argmax(logits[..., 0], -1) == labels)
        return loss, (new_stats, enq, ce, mine, aux, acc)

    def _step(
        self,
        state: TrainState,
        images: jax.Array,
        labels: jax.Array,
        seeds: jax.Array,
        use_mine: jax.Array,
        update_gmm: jax.Array,
        *,
        warm: bool = False,
    ) -> Tuple[TrainState, TrainMetrics]:
        if self._device_augment:
            # uint8 wire -> augmented normalized f32, fused by XLA into the
            # trunk's first conv read (ops/augment.py). Upstream of the
            # grads: images are inputs, not parameters.
            images = augment_tail(images, seeds)
        grad_fn = jax.value_and_grad(self._loss_fn, has_aux=True)
        (loss, (new_stats, enq, ce, mine, aux, acc)), grads = grad_fn(
            state.params, state, images, labels, use_mine
        )

        # divergence guard: a non-finite loss or gradient freezes EVERY state
        # mutation this step — params, optimizer moments, BatchNorm running
        # stats (already poisoned by the forward on a NaN batch), memory
        # enqueue and EM. lax.cond keeps the step pure (no host callback) and
        # skips the update compute at runtime; the host-side policy
        # (resilience.guard.EpochGuard) reads the `nonfinite` metric and
        # rolls back after K consecutive bad steps.
        finite = jnp.isfinite(loss)
        for g in jax.tree_util.tree_leaves(grads):
            # NaN/Inf propagate through the sum: one scalar check per leaf
            finite = finite & jnp.isfinite(jnp.sum(g))

        tx = self.warm_tx if warm else self.joint_tx
        opt_state0 = state.warm_opt_state if warm else state.opt_state

        def _apply(_):
            updates, new_opt = tx.update(grads, opt_state0, state.params)
            new_params = optax.apply_updates(state.params, updates)
            # memory enqueue (reference model.py:228-252, inside forward)
            new_memory = memory_push(state.memory, *enq)
            return new_params, new_opt, new_stats, new_memory

        def _skip(_):
            return state.params, opt_state0, state.batch_stats, state.memory

        params, opt_state, batch_stats, memory = jax.lax.cond(
            finite, _apply, _skip, None
        )

        # EM gate (reference train_and_test.py:61-63): epoch-level flag AND
        # anything in memory AND step % interval == 0 (AND a finite step)
        interval_ok = (state.step % self.cfg.em.update_interval) == 0
        do_em = update_gmm & interval_ok & (jnp.sum(memory.length) > 0) & finite

        def run_em(args):
            gmm, mem, popt = args
            # the score mesh doubles as the EM mesh: both mark the class
            # axis sharded (compaction off, fused E-step shard_mapped)
            gmm, mem, popt, aux_em = em_update(
                gmm, mem, popt, self.proto_tx, self._em_cfg,
                mesh=self._score_mesh,
            )
            return gmm, mem, popt, aux_em.num_active, aux_em.compact_fallback

        def skip_em(args):
            gmm, mem, popt = args
            return (
                gmm, mem, popt,
                jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
            )

        gmm, memory, proto_opt_state, em_active, em_fallback = jax.lax.cond(
            do_em, run_em, skip_em, (state.gmm, memory, state.proto_opt_state)
        )

        new_state = state.replace(
            # step counts ATTEMPTS (a skipped step still advances it, so the
            # host's global-step bookkeeping and the EM interval phase never
            # depend on how many steps diverged)
            step=state.step + 1,
            params=params,
            batch_stats=batch_stats,
            gmm=gmm,
            memory=memory,
            opt_state=state.opt_state if warm else opt_state,
            warm_opt_state=opt_state if warm else state.warm_opt_state,
            proto_opt_state=proto_opt_state,
        )
        metrics = TrainMetrics(
            loss=loss,
            cross_entropy=ce,
            mine=mine,
            aux=aux,
            accuracy=acc,
            full_mem_ratio=jnp.mean(
                (memory.length == memory.capacity).astype(jnp.float32)
            ),
            em_active=em_active,
            em_compact_fallback=em_fallback,
            nonfinite=~finite,
        )
        return new_state, metrics

    def train_step(
        self, state, images, labels, use_mine: bool, update_gmm: bool,
        warm: bool = False, seeds=None,
    ) -> Tuple[TrainState, TrainMetrics]:
        if seeds is None:
            # no loader-shipped seeds (direct callers, tests): a zero
            # stream — only consumed when device_augment is on
            seeds = jnp.zeros((np.shape(images)[0],), jnp.uint32)
        return self._train_step(
            state,
            images,
            labels,
            seeds,
            jnp.asarray(use_mine, jnp.float32),
            jnp.asarray(update_gmm, bool),
            warm=warm,
        )

    # ------------------------------------------------------------------- eval
    def _eval(
        self, state: TrainState, images: jax.Array, labels: Optional[jax.Array]
    ) -> EvalOutput:
        (proto_map, _), _ = self._apply(
            state.params, state.batch_stats, images, train=False
        )
        logits, _, _ = head_forward(
            proto_map, state.gmm, None, self.cfg.model.mine_T,
            fused=self._fused, mesh=self._score_mesh,
        )
        lvl0 = logits[..., 0]
        correct = (
            (jnp.argmax(lvl0, -1) == labels)
            if labels is not None
            else jnp.zeros(lvl0.shape[0], bool)
        )
        return EvalOutput(logits=lvl0, log_px=log_px(lvl0), correct=correct)

    def eval_step(self, state, images, labels=None) -> EvalOutput:
        return self._eval_step(state, images, labels)

    # ------------------------------------------------------------ epoch gates
    def epoch_flags(self, state: TrainState, epoch: int) -> Dict[str, bool]:
        """Python-side epoch gating (reference main.py:237-238)."""
        s = self.cfg.schedule
        all_full = bool(
            jax.device_get(
                jnp.all(state.memory.length == state.memory.capacity)
            )
        )
        return {
            "warm": epoch < s.num_warm_epochs,
            "use_mine": epoch >= s.mine_start,
            "update_gmm": (epoch >= s.update_gmm_start) and all_full,
        }

    def put_batch(self, batch):
        """(images, labels[, seeds]) host arrays -> device arrays (async
        placement). uint8 images stay uint8 — the 4x-smaller wire format
        crosses PCIe as-is and widens on device (ops/augment.py).
        ShardedTrainer overrides with the mesh-sharded multi-host variant."""
        images = np.asarray(batch[0])
        if images.dtype != np.uint8:
            images = images.astype(np.float32, copy=False)
        out = (images, np.asarray(batch[1], np.int32))
        if len(batch) > 2:
            out = out + (np.asarray(batch[2], np.uint32),)
        return jax.device_put(out)

    def train_epoch(self, state, batches, epoch: int, monitor=None,
                    guard=None):
        """Drive one epoch over an iterable of (images, labels) host batches.

        Batches are device-prefetched (data/loader.py device_prefetch): batch
        N+1's host->device copy overlaps step N's compute — the first
        post-55.8%-MFU lever named in PERF.md.

        `monitor` (a telemetry StepMonitor) observes each step: wall time,
        throughput, batch transfer bytes, loader wait (the blocking part of
        the batch fetch, gauged as `loader_wait_fraction` of epoch wall
        time), recompile detection. Each interval
        runs from the END of the previous step call to the end of this one,
        so loader/prefetch wait is charged to the step that waited — the
        intervals sum to true epoch wall time and an input-bound epoch shows
        up as slow steps, not as phantom throughput. Observation never syncs
        the device: a single interval is dispatch+wait time, but the queue
        must drain across the epoch, so EMA/throughput are honest in steady
        state.

        The returned metrics are the LAST step's, except `em_active` and
        `full_mem_ratio`, which are epoch maxima, and
        `em_compact_fallback`, which is the epoch SUM (the telemetry
        counter increments by it): EM width varies per step with batch
        label composition (the step where queues first fill can touch every
        class at once), so a last-step sample would understate it. The
        accumulators run on-device (no per-step host sync).

        `guard` (a resilience EpochGuard) wraps the batch stream (chaos
        injection) and observes each completed step: it may STOP the epoch
        (preemption — the in-flight step finishes first, matching the
        SIGTERM contract) or raise DivergenceError (consecutive non-finite
        steps — the driver rolls back). The guard's accounting runs on
        device at step cadence; host syncs only at its check_every cadence."""
        import time

        from mgproto_tpu.data.loader import device_prefetch
        from mgproto_tpu.telemetry.monitor import tree_transfer_bytes

        flags = self.epoch_flags(state, epoch)
        if guard is not None:
            guard.begin_epoch(epoch, state)
            batches = guard.wrap_batches(batches)
        last = None
        em_max = fm_max = fb_sum = None
        t_prev = time.perf_counter()
        prefetched = device_prefetch(
            batches, self.put_batch, depth=self.cfg.data.prefetch_depth
        )
        while True:
            # time the fetch separately: this is where an input-bound epoch
            # blocks (loader decode/IPC; the H2D copy itself is async), and
            # it feeds the `loader_wait_fraction` gauge
            t_fetch = time.perf_counter()
            batch = next(prefetched, None)
            if batch is None:
                break
            wait_s = time.perf_counter() - t_fetch
            images, labels = batch[0], batch[1]
            # already device-placed: train_step sees jax.Arrays and skips
            # its host-conversion path
            state, last = self.train_step(
                state,
                images,
                labels,
                use_mine=flags["use_mine"],
                update_gmm=flags["update_gmm"],
                warm=flags["warm"],
                seeds=batch[2] if len(batch) > 2 else None,
            )
            if monitor is not None:
                now = time.perf_counter()
                monitor.observe_step(
                    int(images.shape[0]),
                    now - t_prev,
                    transfer_bytes=tree_transfer_bytes(batch),
                    wait_seconds=wait_s,
                )
                t_prev = now
            em_max = (
                last.em_active if em_max is None
                else jnp.maximum(em_max, last.em_active)
            )
            fm_max = (
                last.full_mem_ratio if fm_max is None
                else jnp.maximum(fm_max, last.full_mem_ratio)
            )
            fb_sum = (
                last.em_compact_fallback if fb_sum is None
                else fb_sum + last.em_compact_fallback
            )
            if guard is not None and guard.after_step(state, last):
                break  # preemption: stop AFTER the completed step
        if guard is not None:
            guard.end_epoch()
        if last is not None:
            last = last._replace(
                em_active=em_max, full_mem_ratio=fm_max,
                em_compact_fallback=fb_sum,
            )
        return state, last
