"""Jitted train/eval steps + epoch drivers.

Reference: train_and_test.py. One fused, jitted step does what the reference
spreads over forward / backward / optimizer / memory enqueue / EM call
(train_and_test.py:26-64): the EM update runs INSIDE the step under lax.cond
(reference calls model.module.update_GMM() every iteration once gated —
bypassing DataParallel; here it's just more of the same jitted program, so it
shards with the rest).

Dynamic gates (`use_mine`, `update_gmm`) are traced scalars, not python
bools — flipping them mid-training does not retrigger compilation. The
warm/joint phase IS a static switch (two optimizers with different
topologies, reference main.py:205-220), giving two compiled variants.

Async bank pipeline (`EMConfig.async_bank`, PERF.md lever 6): the step is
internally two phases with no backward data dependence between them —
a TRUNK (forward + losses + backward + optimizer) and a BANK (memory
enqueue + EM). Batch N's bank output is only *read* by batch N+1's trunk
(scoring against the updated prototypes), so the pipeline may legally run
one step behind: with the flag on, the bank program for batch N is
dispatched right AFTER batch N+1's trunk, scoring consumes ONE-STEP-STALE
prototypes (deterministic — parity-pinned against a hand-rolled oracle in
tests/test_async_bank.py), and the bank/EM buffers are donated to the bank
program so the [C, cap, d] bank is updated in place instead of copied
through HBM every step. Flag off compiles both phases into the one
monolithic program (`_step`) — same ops, same order, bit-exact to the
pre-pipeline step. Both phases share single definitions (`_trunk_step`,
`core.em.bank_update`) so the two modes cannot drift.
"""

from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mgproto_tpu.config import Config
from mgproto_tpu.core import losses as L
from mgproto_tpu.core.em import bank_update, make_mean_optimizer, resolve_em_config
from mgproto_tpu.core.mgproto import (
    MGProtoFeatures,
    head_forward,
    log_px,
)
from mgproto_tpu.core.state import (
    BankState,
    TrainState,
    TrunkState,
    create_train_state,
    make_joint_optimizer,
    make_warm_optimizer,
    merge_state,
    split_state,
)
from mgproto_tpu.ops.augment import augment_tail, resolve_device_augment
from mgproto_tpu.perf.precision import resolve_policy


def resolve_async_bank(flag: Optional[bool]) -> bool:
    """Resolve `EMConfig.async_bank` (None = auto, like fused_scoring): the
    pipeline only pays off where the bank phase is real device time on the
    step's critical path — TPU. The ONE definition of the auto rule —
    Trainer and the HBM planner's candidate builder (perf/planner.py) both
    use it, so the planner can never measure a different mode than the run
    executes. Explicit True/False always honored (tests force ON on CPU)."""
    if flag is not None:
        return bool(flag)
    return jax.default_backend() == "tpu"


class TrainMetrics(NamedTuple):
    loss: jax.Array
    cross_entropy: jax.Array
    mine: jax.Array
    aux: jax.Array
    accuracy: jax.Array
    full_mem_ratio: jax.Array  # fraction of classes with a full queue
    em_active: jax.Array  # classes EM touched this step
    # EM calls that exceeded the compact width and took the dense lax.cond
    # fallback (core/em.py; per-step 0/1, epoch SUM after train_epoch)
    em_compact_fallback: jax.Array
    nonfinite: jax.Array  # bool: this step's update was SKIPPED (bad loss/grads)


class TrunkOut(NamedTuple):
    """Everything the trunk program hands the bank phase + step metrics.
    The enqueue candidates and gates cross the program boundary as OUTPUTS
    (fresh buffers): under the async pipeline the host holds them for one
    step, and they must stay valid after the trunk's donated inputs die."""

    enq_feats: jax.Array  # [B*K, d] memory-enqueue candidates
    enq_classes: jax.Array  # [B*K] int32
    enq_valid: jax.Array  # [B*K] bool
    step0: jax.Array  # the PRE-increment step counter (EM interval phase)
    finite: jax.Array  # bool: loss/grads finite (divergence guard gate)
    loss: jax.Array
    cross_entropy: jax.Array
    mine: jax.Array
    aux: jax.Array
    accuracy: jax.Array


class BankStepOut(NamedTuple):
    """Bank-program scalars folded into TrainMetrics (one step late under
    the async pipeline)."""

    num_active: jax.Array  # classes EM touched
    compact_fallback: jax.Array  # 0/1: dense lax.cond fallback taken
    full_mem_ratio: jax.Array  # fraction of classes with a full queue


class EvalOutput(NamedTuple):
    logits: jax.Array  # [B, C] level-0 class log-likelihoods
    log_px: jax.Array  # [B] log p(x) OoD score
    correct: jax.Array  # [B] bool (vs labels if given, else False)


class Trainer:
    """Owns the model + optimizers (static) and the jitted step functions.

    All state flows through `TrainState`; nothing here mutates."""

    def __init__(self, cfg: Config, steps_per_epoch: int, donate: bool = False):
        self.cfg = cfg
        self.steps_per_epoch = steps_per_epoch
        self.donate = donate
        self.model = MGProtoFeatures(cfg=cfg.model)
        # fused_scoring=None resolves per backend: the Pallas kernel measured
        # 1.9x faster than the XLA path on real TPU (BENCH_PROBE_RUN.json)
        # so TPU defaults to it; CPU/GPU fall back to the XLA path (the
        # interpret-mode kernel is correct but slow). On class-sharded meshes
        # ShardedTrainer keeps the kernel via shard_map (_score_mesh below),
        # dropping to the XLA path only when num_classes cannot shard over
        # the model axis. Explicit True/False is always honored.
        self._fused = self._resolve_fused(cfg.model.fused_scoring)
        # set by ShardedTrainer when the class axis is sharded: head_forward
        # then shard_maps the Pallas kernel over this mesh (core/mgproto.py)
        self._score_mesh = None
        # uint8 wire format + device augmentation tail (ops/augment.py):
        # flip + b/c/s jitter + normalize run inside the jitted step on the
        # u8 batch, per-sample seeded. Resolved like fused_scoring (auto =
        # TPU); a static python bool, so the traced program has no augment
        # code at all when off.
        self._device_augment = resolve_device_augment(cfg.data.device_augment)
        # the mixed-precision policy (perf/precision.py): validates the
        # configured compute_dtype up front and is the provenance block
        # telemetry meta + exported artifacts record. The trunk honors
        # compute_dtype via the model's flax dtype; the bank phase's f32-
        # statistics invariant is asserted at trace time in core/em.py.
        self.precision = resolve_policy(cfg)
        self.joint_tx = make_joint_optimizer(cfg, steps_per_epoch)
        self.warm_tx = make_warm_optimizer(cfg)
        self.proto_tx = make_mean_optimizer(cfg.em)
        # compact dirty-class EM: auto width resolves to the GLOBAL batch
        # (one step can newly dirty at most one class per batch row), so the
        # dense fallback fires only when EM was gated off long enough for
        # dirt to accumulate (core/em.py resolve_em_config)
        self._em_cfg = resolve_em_config(
            cfg.em,
            cfg.model.num_classes,
            cfg.data.train_batch_size * jax.process_count(),
        )
        # donate=True reuses the incoming state's buffers (params + opt
        # moments + memory bank, ~300 MB at flagship scale) in place instead
        # of copying each step. The production drivers (cli.train, bench.py)
        # enable it and always rebind `state` to the returned one; it stays
        # off by default so interactive callers may re-step an old state.
        self._train_step = jax.jit(
            self._step,
            static_argnames=("warm",),
            donate_argnums=(0,) if donate else (),
        )
        # async bank pipeline (module docstring): a static python bool —
        # OFF never touches the pipeline code paths at all
        self._async_bank = resolve_async_bank(cfg.em.async_bank)
        # the split programs. Compiled lazily on first use, so a sync run
        # never pays for them; the bank program donates the bank/EM buffers
        # under the same `donate` contract as the monolithic state donation
        # above — the [C, cap, d] bank is then updated in place.
        self._trunk_jit = jax.jit(
            self._trunk_step,
            static_argnames=("warm",),
            donate_argnums=(0,) if donate else (),
        )
        self._bank_jit = jax.jit(
            self._bank_step, donate_argnums=(0,) if donate else ()
        )
        # pipeline registers (async mode only): the held enqueue candidates
        # of the newest trunk (dispatched as a bank program one step later),
        # and the per-step host-side overlap window behind telemetry's
        # `bank_dispatch_overlap_fraction` gauge (StepMonitor accumulates
        # the epoch fraction — the one owner of that metric)
        self._held_enq = None
        self._bank_dispatch_t: Optional[float] = None
        self._bank_overlap_step_s = 0.0
        self._zero_bank_out = None
        self._eval_step = jax.jit(self._eval)
        # the live jit callables, for telemetry's recompile detection
        # (StepMonitor reads their _cache_size deltas). ShardedTrainer
        # rebinds this when it builds its sharded jits.
        self._jit_handles = [
            self._train_step, self._trunk_jit, self._bank_jit,
            self._eval_step,
        ]

    @property
    def jit_handles(self):
        """Current jitted step callables (telemetry watches these for
        cache-miss/recompile growth)."""
        return list(self._jit_handles)

    def _resolve_fused(self, fused: Optional[bool]) -> bool:
        if fused is not None:
            return fused
        return jax.default_backend() == "tpu"

    @property
    def async_bank(self) -> bool:
        """Resolved async-bank mode (telemetry meta records this)."""
        return self._async_bank

    def init_state(self, rng: jax.Array, for_restore: bool = False) -> TrainState:
        """`for_restore=True` builds a restore TARGET: skips the pretrained
        trunk load (every weight is about to be overwritten by the orbax
        restore, and eval hosts need not carry the torch .pth)."""
        state, _ = create_train_state(
            self.cfg,
            self.steps_per_epoch,
            rng,
            model=self.model,
            joint_tx=self.joint_tx,
            warm_tx=self.warm_tx,
            proto_tx=self.proto_tx,
            for_restore=for_restore,
        )
        return state

    # ------------------------------------------------------------------ train
    def _apply(
        self, params, batch_stats, images, train: bool
    ) -> Tuple[Tuple[jax.Array, jax.Array], Any]:
        variables = {"params": params["net"], "batch_stats": batch_stats}
        if train:
            (proto_map, embed), mut = self.model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            return (proto_map, embed), mut["batch_stats"]
        proto_map, embed = self.model.apply(variables, images, train=False)
        return (proto_map, embed), batch_stats

    def _loss_fn(
        self, params, batch_stats, gmm, images, labels, use_mine: jax.Array
    ):
        (proto_map, embed), new_stats = self._apply(
            params, batch_stats, images, train=True
        )
        logits, pooled, enq = head_forward(
            proto_map, gmm, labels, self.cfg.model.mine_T,
            fused=self._fused, mesh=self._score_mesh,
        )
        ce = L.cross_entropy(logits[..., 0], labels)
        mine = L.mine_loss(logits, labels) * use_mine
        aux_fn = L.AUX_LOSSES[self.cfg.loss.aux_loss]
        if self.cfg.loss.aux_loss in L.PROXY_BASED:
            aux = aux_fn(embed, labels, params["proxies"])
        else:
            aux = aux_fn(embed, labels)
        c = self.cfg.loss
        loss = c.crs_ent * ce + c.mine * mine + c.aux * aux
        acc = jnp.mean(jnp.argmax(logits[..., 0], -1) == labels)
        return loss, (new_stats, enq, ce, mine, aux, acc)

    def _trunk_step(
        self,
        trunk: TrunkState,
        gmm,
        images: jax.Array,
        labels: jax.Array,
        seeds: jax.Array,
        use_mine: jax.Array,
        *,
        warm: bool = False,
    ) -> Tuple[TrunkState, TrunkOut]:
        """TRUNK program: forward + losses + backward + optimizer. Scores
        against `gmm` but never mutates it; the enqueue candidates and the
        gates the bank phase needs come back as outputs. The monolithic step
        inlines this; the async pipeline compiles it standalone (donating
        `trunk`, NOT `gmm` — the held bank program still owns that)."""
        if self._device_augment:
            # uint8 wire -> augmented normalized f32, fused by XLA into the
            # trunk's first conv read (ops/augment.py). Upstream of the
            # grads: images are inputs, not parameters.
            images = augment_tail(images, seeds)
        grad_fn = jax.value_and_grad(self._loss_fn, has_aux=True)
        (loss, (new_stats, enq, ce, mine, aux, acc)), grads = grad_fn(
            trunk.params, trunk.batch_stats, gmm, images, labels, use_mine
        )

        # divergence guard: a non-finite loss or gradient freezes EVERY state
        # mutation this step — params, optimizer moments, BatchNorm running
        # stats (already poisoned by the forward on a NaN batch), and via the
        # exported `finite` gate the memory enqueue and EM too. lax.cond
        # keeps the step pure (no host callback) and skips the update compute
        # at runtime; the host-side policy (resilience.guard.EpochGuard)
        # reads the `nonfinite` metric and rolls back after K consecutive
        # bad steps.
        finite = jnp.isfinite(loss)
        for g in jax.tree_util.tree_leaves(grads):
            # NaN/Inf propagate through the sum: one scalar check per leaf
            finite = finite & jnp.isfinite(jnp.sum(g))

        tx = self.warm_tx if warm else self.joint_tx
        opt_state0 = trunk.warm_opt_state if warm else trunk.opt_state

        def _apply(_):
            updates, new_opt = tx.update(grads, opt_state0, trunk.params)
            new_params = optax.apply_updates(trunk.params, updates)
            return new_params, new_opt, new_stats

        def _skip(_):
            return trunk.params, opt_state0, trunk.batch_stats

        params, opt_state, batch_stats = jax.lax.cond(
            finite, _apply, _skip, None
        )
        new_trunk = TrunkState(
            # step counts ATTEMPTS (a skipped step still advances it, so the
            # host's global-step bookkeeping and the EM interval phase never
            # depend on how many steps diverged)
            step=trunk.step + 1,
            params=params,
            batch_stats=batch_stats,
            opt_state=trunk.opt_state if warm else opt_state,
            warm_opt_state=opt_state if warm else trunk.warm_opt_state,
        )
        return new_trunk, TrunkOut(
            enq_feats=enq[0],
            enq_classes=enq[1],
            enq_valid=enq[2],
            step0=trunk.step,
            finite=finite,
            loss=loss,
            cross_entropy=ce,
            mine=mine,
            aux=aux,
            accuracy=acc,
        )

    def _bank_step(
        self,
        bank: BankState,
        feats: jax.Array,
        classes: jax.Array,
        valid: jax.Array,
        step0: jax.Array,
        update_gmm: jax.Array,
        finite: jax.Array,
    ) -> Tuple[BankState, BankStepOut]:
        """BANK program: memory enqueue + gated EM (the one shared
        definition, core.em.bank_update). Compiled standalone for the async
        pipeline with `bank` donated: gmm/memory/EM-moment buffers are
        updated in place. The score mesh doubles as the EM mesh — both mark
        the class axis sharded (compaction off, fused E-step shard_mapped),
        and the EM sufficient statistics stay correct under one-step
        staleness because the collective pattern is unchanged: every shard
        runs the SAME (stale) schedule, so the psum'd statistics of a given
        bank generation are the sync step's statistics, one step late."""
        gmm, memory, popt, baux = bank_update(
            bank.gmm, bank.memory, bank.proto_opt_state,
            self.proto_tx, self._em_cfg,
            feats, classes, valid, step0, update_gmm, finite,
            mesh=self._score_mesh,
        )
        out = BankStepOut(
            num_active=baux.num_active,
            compact_fallback=baux.compact_fallback,
            full_mem_ratio=jnp.mean(
                (memory.length == memory.capacity).astype(jnp.float32)
            ),
        )
        return BankState(gmm=gmm, memory=memory, proto_opt_state=popt), out

    def _step(
        self,
        state: TrainState,
        images: jax.Array,
        labels: jax.Array,
        seeds: jax.Array,
        use_mine: jax.Array,
        update_gmm: jax.Array,
        *,
        warm: bool = False,
    ) -> Tuple[TrainState, TrainMetrics]:
        """The monolithic (sync) step: trunk + bank phases in ONE compiled
        program — `--async_bank` off. Same phase definitions as the
        pipelined mode, fused by XLA exactly as before the split."""
        trunk0, bank0 = split_state(state)
        new_trunk, out = self._trunk_step(
            trunk0, bank0.gmm, images, labels, seeds, use_mine, warm=warm
        )
        new_bank, bank_out = self._bank_step(
            bank0, out.enq_feats, out.enq_classes, out.enq_valid,
            out.step0, update_gmm, out.finite,
        )
        metrics = TrainMetrics(
            loss=out.loss,
            cross_entropy=out.cross_entropy,
            mine=out.mine,
            aux=out.aux,
            accuracy=out.accuracy,
            full_mem_ratio=bank_out.full_mem_ratio,
            em_active=bank_out.num_active,
            em_compact_fallback=bank_out.compact_fallback,
            nonfinite=~out.finite,
        )
        return merge_state(new_trunk, new_bank), metrics

    # ------------------------------------------------- async bank pipeline
    def _zero_bank_metrics(self) -> BankStepOut:
        """Placeholder bank metrics for the pipeline's fill step (no bank
        output exists yet); cached so it costs one placement per run."""
        if self._zero_bank_out is None:
            self._zero_bank_out = BankStepOut(
                num_active=jnp.zeros((), jnp.int32),
                compact_fallback=jnp.zeros((), jnp.int32),
                full_mem_ratio=jnp.zeros((), jnp.float32),
            )
        return self._zero_bank_out

    def _dispatch_pending_bank(
        self, bank: BankState
    ) -> Tuple[BankState, Optional[BankStepOut]]:
        """Dispatch the HELD bank program (the previous batch's enqueue +
        EM) against `bank`. Dispatch ORDER is load-bearing: the current
        batch's trunk must already be in flight reading `bank.gmm` before
        this call donates it — in-flight reads are sequenced by the runtime,
        later host reads are use-after-donate errors. After the dispatch
        below the donated operands are dead to the host;
        scripts/check_bank_donation.py lints that `bank` is never
        referenced past the dispatch line."""
        held = self._held_enq
        if held is None:
            return bank, None
        self._held_enq = None
        new_bank, bank_out = self._bank_jit(bank, *held)
        # opens the overlap window the NEXT trunk dispatch closes (the
        # bank_dispatch_overlap_fraction gauge)
        self._bank_dispatch_t = time.perf_counter()
        return new_bank, bank_out

    def _async_train_step(
        self, state, images, labels, seeds, use_mine, update_gmm, warm
    ) -> Tuple[TrainState, TrainMetrics]:
        """One pipelined step: dispatch batch N's trunk against the NEWEST
        COMPLETED bank generation (one-step-stale prototypes), then dispatch
        batch N-1's held bank program, then hold batch N's enqueue
        candidates for the next call. Metrics mix batch N's trunk scalars
        with batch N-1's bank scalars (zeros on the fill step)."""
        trunk0, bank0 = split_state(state)
        new_trunk, out = self._trunk_jit(
            trunk0, bank0.gmm, images, labels, seeds, use_mine, warm=warm
        )
        now = time.perf_counter()
        if self._bank_dispatch_t is not None:
            # close the overlap window: the previously dispatched bank
            # program was in flight across this step's fetch + trunk
            # dispatch. Host dispatch-clock estimate, an upper bound on
            # true device overlap — honest about whether the pipeline ran
            # pipelined; train_epoch feeds it to the StepMonitor gauge.
            self._bank_overlap_step_s = now - self._bank_dispatch_t
            self._bank_dispatch_t = None
        else:
            self._bank_overlap_step_s = 0.0
        new_bank, bank_out = self._dispatch_pending_bank(bank0)
        self._held_enq = (
            out.enq_feats, out.enq_classes, out.enq_valid,
            out.step0, update_gmm, out.finite,
        )
        if bank_out is None:
            bank_out = self._zero_bank_metrics()
        metrics = TrainMetrics(
            loss=out.loss,
            cross_entropy=out.cross_entropy,
            mine=out.mine,
            aux=out.aux,
            accuracy=out.accuracy,
            full_mem_ratio=bank_out.full_mem_ratio,
            em_active=bank_out.num_active,
            em_compact_fallback=bank_out.compact_fallback,
            nonfinite=~out.finite,
        )
        return merge_state(new_trunk, new_bank), metrics

    def flush_bank(
        self, state: TrainState
    ) -> Tuple[TrainState, Optional[BankStepOut]]:
        """Drain the pipeline: dispatch the held bank program (the LAST
        batch's enqueue + EM) and fold its output into `state`. Must run
        before anything reads the bank state as current — epoch end,
        checkpointing, eval; train_epoch calls it at every exit. No-op in
        sync mode or when nothing is held."""
        if self._held_enq is None:
            return state, None
        trunk, bank = split_state(state)
        new_bank, bank_out = self._dispatch_pending_bank(bank)
        self._bank_dispatch_t = None  # no trunk follows: nothing overlaps
        return merge_state(trunk, new_bank), bank_out

    def reset_bank_pipeline(self) -> None:
        """Discard any held (undispatched) bank work + overlap clocks. Run
        at epoch start: after a mid-epoch exception (divergence rollback),
        the held candidates refer to a state that no longer exists."""
        self._held_enq = None
        self._bank_dispatch_t = None
        self._bank_overlap_step_s = 0.0

    def train_step(
        self, state, images, labels, use_mine: bool, update_gmm: bool,
        warm: bool = False, seeds=None,
    ) -> Tuple[TrainState, TrainMetrics]:
        if seeds is None:
            # no loader-shipped seeds (direct callers, tests): a zero
            # stream — only consumed when device_augment is on
            seeds = jnp.zeros((np.shape(images)[0],), jnp.uint32)
        use_mine = jnp.asarray(use_mine, jnp.float32)
        update_gmm = jnp.asarray(update_gmm, bool)
        if self._async_bank:
            return self._async_train_step(
                state, images, labels, seeds, use_mine, update_gmm, warm
            )
        return self._train_step(
            state, images, labels, seeds, use_mine, update_gmm, warm=warm
        )

    # ------------------------------------------------------------------- eval
    def _eval(
        self, state: TrainState, images: jax.Array, labels: Optional[jax.Array]
    ) -> EvalOutput:
        (proto_map, _), _ = self._apply(
            state.params, state.batch_stats, images, train=False
        )
        logits, _, _ = head_forward(
            proto_map, state.gmm, None, self.cfg.model.mine_T,
            fused=self._fused, mesh=self._score_mesh,
        )
        lvl0 = logits[..., 0]
        correct = (
            (jnp.argmax(lvl0, -1) == labels)
            if labels is not None
            else jnp.zeros(lvl0.shape[0], bool)
        )
        return EvalOutput(logits=lvl0, log_px=log_px(lvl0), correct=correct)

    def eval_step(self, state, images, labels=None) -> EvalOutput:
        return self._eval_step(state, images, labels)

    # ------------------------------------------------------------ epoch gates
    def epoch_flags(self, state: TrainState, epoch: int) -> Dict[str, bool]:
        """Python-side epoch gating (reference main.py:237-238)."""
        s = self.cfg.schedule
        all_full = bool(
            jax.device_get(
                jnp.all(state.memory.length == state.memory.capacity)
            )
        )
        return {
            "warm": epoch < s.num_warm_epochs,
            "use_mine": epoch >= s.mine_start,
            "update_gmm": (epoch >= s.update_gmm_start) and all_full,
        }

    def put_batch(self, batch):
        """(images, labels[, seeds]) host arrays -> device arrays (async
        placement). uint8 images stay uint8 — the 4x-smaller wire format
        crosses PCIe as-is and widens on device (ops/augment.py).
        ShardedTrainer overrides with the mesh-sharded multi-host variant."""
        images = np.asarray(batch[0])
        if images.dtype != np.uint8:
            images = images.astype(np.float32, copy=False)
        out = (images, np.asarray(batch[1], np.int32))
        if len(batch) > 2:
            out = out + (np.asarray(batch[2], np.uint32),)
        return jax.device_put(out)

    def train_epoch(self, state, batches, epoch: int, monitor=None,
                    guard=None, window=None, fleet=None):
        """Drive one epoch over an iterable of (images, labels) host batches.

        Batches are device-prefetched (data/loader.py device_prefetch): batch
        N+1's host->device copy overlaps step N's compute — the first
        post-55.8%-MFU lever named in PERF.md.

        `monitor` (a telemetry StepMonitor) observes each step: wall time,
        throughput, batch transfer bytes, loader wait (the blocking part of
        the batch fetch, gauged as `loader_wait_fraction` of epoch wall
        time), recompile detection. Each interval
        runs from the END of the previous step call to the end of this one,
        so loader/prefetch wait is charged to the step that waited — the
        intervals sum to true epoch wall time and an input-bound epoch shows
        up as slow steps, not as phantom throughput. Observation never syncs
        the device: a single interval is dispatch+wait time, but the queue
        must drain across the epoch, so EMA/throughput are honest in steady
        state.

        The returned metrics are the LAST step's, except `em_active` and
        `full_mem_ratio`, which are epoch maxima, and
        `em_compact_fallback`, which is the epoch SUM (the telemetry
        counter increments by it): EM width varies per step with batch
        label composition (the step where queues first fill can touch every
        class at once), so a last-step sample would understate it. The
        accumulators run on-device (no per-step host sync).

        `guard` (a resilience EpochGuard) wraps the batch stream (chaos
        injection) and observes each completed step: it may STOP the epoch
        (preemption — the in-flight step finishes first, matching the
        SIGTERM contract) or raise DivergenceError (consecutive non-finite
        steps — the driver rolls back). The guard's accounting runs on
        device at step cadence; host syncs only at its check_every cadence.

        Async bank mode: the pipeline registers are reset on entry (a
        previous epoch that exited through an exception may have left stale
        held work), the final held bank program is FLUSHED on every exit
        path (normal end and guard-preemption stop both fall through the
        flush below), its metrics fold into the epoch accumulators, and
        each step's bank-in-flight window feeds the monitor's
        `bank_dispatch_overlap_fraction` gauge.

        `window` (an obs.profiler.ProfilerWindow) observes each step too:
        it arms/disarms `jax.profiler` capture on its configured step range
        or anomaly triggers (spike vs EMA, recompile via `monitor`,
        loader-wait fraction). Every step also lands on the process flight
        recorder's ring, so a failure dump shows the steps leading up to
        it.

        `fleet` (an obs.fleet.SkewMonitor, multi-host runs only) gets each
        step's wall time as its fallback step-EMA denominator — the
        barrier-arrival skew it accumulates (via the multihost skew
        observer) is reported as a FRACTION of step time, and must stay
        meaningful even when telemetry is disabled."""
        from mgproto_tpu.data.loader import device_prefetch
        from mgproto_tpu.obs.flightrec import record_event
        from mgproto_tpu.parallel.multihost import heartbeat_tick
        from mgproto_tpu.telemetry.monitor import tree_transfer_bytes

        self.reset_bank_pipeline()
        flags = self.epoch_flags(state, epoch)
        if guard is not None:
            guard.begin_epoch(epoch, state)
            batches = guard.wrap_batches(batches)
        last = None
        em_max = fm_max = fb_sum = None
        step_i = 0
        t_prev = time.perf_counter()
        prefetched = device_prefetch(
            batches, self.put_batch, depth=self.cfg.data.prefetch_depth
        )
        while True:
            # time the fetch separately: this is where an input-bound epoch
            # blocks (loader decode/IPC; the H2D copy itself is async), and
            # it feeds the `loader_wait_fraction` gauge
            t_fetch = time.perf_counter()
            batch = next(prefetched, None)
            if batch is None:
                break
            wait_s = time.perf_counter() - t_fetch
            images, labels = batch[0], batch[1]
            # already device-placed: train_step sees jax.Arrays and skips
            # its host-conversion path
            state, last = self.train_step(
                state,
                images,
                labels,
                use_mine=flags["use_mine"],
                update_gmm=flags["update_gmm"],
                warm=flags["warm"],
                seeds=batch[2] if len(batch) > 2 else None,
            )
            now = time.perf_counter()
            step_s = now - t_prev
            t_prev = now
            if monitor is not None:
                monitor.observe_step(
                    int(images.shape[0]),
                    step_s,
                    transfer_bytes=tree_transfer_bytes(batch),
                    wait_seconds=wait_s,
                    bank_overlap_seconds=self._bank_overlap_step_s,
                )
            wait_frac = wait_s / step_s if step_s > 0 else 0.0
            record_event(
                "step", epoch=epoch, i=step_i,
                seconds=round(step_s, 6), wait_s=round(wait_s, 6),
            )
            # liveness signal for the guarded-barrier protocol: a peer that
            # misses a barrier with a FRESH heartbeat is wedged mid-step,
            # one with a stale heartbeat is dead. No-op unless a barrier
            # guard is configured (multi-host runs with --barrier_timeout_s)
            heartbeat_tick()
            step_i += 1
            if window is not None:
                window.on_step(step_s, wait_fraction=wait_frac)
            if fleet is not None:
                fleet.observe_step(step_s)
            em_max = (
                last.em_active if em_max is None
                else jnp.maximum(em_max, last.em_active)
            )
            fm_max = (
                last.full_mem_ratio if fm_max is None
                else jnp.maximum(fm_max, last.full_mem_ratio)
            )
            fb_sum = (
                last.em_compact_fallback if fb_sum is None
                else fb_sum + last.em_compact_fallback
            )
            if guard is not None and guard.after_step(state, last):
                break  # preemption: stop AFTER the completed step
        # async mode: the last batch's bank program is still held — drain it
        # so the returned state's bank fields are CURRENT (epoch_flags, the
        # test pass, checkpoints and eval all read them next)
        state, flushed = self.flush_bank(state)
        if flushed is not None:
            em_max = (
                flushed.num_active if em_max is None
                else jnp.maximum(em_max, flushed.num_active)
            )
            fm_max = (
                flushed.full_mem_ratio if fm_max is None
                else jnp.maximum(fm_max, flushed.full_mem_ratio)
            )
            fb_sum = (
                flushed.compact_fallback if fb_sum is None
                else fb_sum + flushed.compact_fallback
            )
        if guard is not None:
            guard.end_epoch()
        if last is not None:
            last = last._replace(
                em_active=em_max, full_mem_ratio=fm_max,
                em_compact_fallback=fb_sum,
            )
        return state, last
