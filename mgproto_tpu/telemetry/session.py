"""TelemetrySession: one run's telemetry wiring + on-disk artifacts.

Owns (or borrows) a registry and tracer, a StepMonitor and a ModelHealth
recorder, and writes a telemetry directory:

    metrics.prom    latest Prometheus text snapshot (atomic overwrite)
    metrics.jsonl   one registry snapshot per flush (summarize input)
    health.jsonl    one ModelHealth record per epoch
    trace.json      Chrome-trace export of the span tracer

Multi-host (ISSUE 10 fleet observatory): every process computes (SPMD steps
and the scalar health diagnostics need all hosts), and every process SINKS —
host 0 keeps the canonical unsuffixed files (all existing tooling reads
them unchanged), while process p > 0 writes host-tagged SIDECAR streams
next to them (`metrics.jsonl.h<p>`, `metrics.prom.h<p>`, `health.jsonl.h<p>`,
`trace.json.h<p>` — the PR-9 log-suffix convention). Every JSONL snapshot
record carries a top-level `host` field so merged streams stay
attributable; `mgproto-telemetry fleet` joins host 0 + sidecars into the
per-host table. meta.json stays host-0-only (run config is run-wide).
Single process resolves to host 0 and takes the exact pre-sidecar path —
no suffix, no extra work. Cross-host throughput goes through
`parallel.multihost.allgather_sum` in `end_epoch` (every process must call
it: it is a collective).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from mgproto_tpu.telemetry.health import ModelHealth
from mgproto_tpu.telemetry.monitor import StepMonitor
from mgproto_tpu.telemetry.registry import (
    JsonlWriter,
    MetricRegistry,
    write_jsonl_snapshot,
)
from mgproto_tpu.telemetry.tracing import Tracer

PROM_FILE = "metrics.prom"
METRICS_FILE = "metrics.jsonl"
HEALTH_FILE = "health.jsonl"
TRACE_FILE = "trace.json"
META_FILE = "meta.json"

# EM fast-path metrics (core/em.py): pre-registered so a clean run's
# snapshots carry explicit values and `mgproto-telemetry summarize` always
# shows the EM story
EM_ACTIVE_GAUGE = "em_active_classes"
EM_FALLBACK_COUNTER = "em_compact_fallback_total"

# async bank pipeline + HBM auto-tuner (engine/train.py, perf/planner.py):
# the overlap gauge is created by the session's StepMonitor; the rejection
# counter is pre-registered here so a run that never auto-tuned (or whose
# every candidate fit) still reports an explicit zero
BANK_OVERLAP_GAUGE = "bank_dispatch_overlap_fraction"
AUTOTUNE_REJECTED_COUNTER = "autotune_plan_rejected_total"

# input-pipeline metrics (data/loader.py + StepMonitor): pre-registered so
# summarize always shows the data story — a run that never waited on its
# loader (or never used shm slabs) reports explicit zeros
DATA_WAIT_GAUGE = "loader_wait_fraction"
DATA_SHM_SLABS_GAUGE = "loader_shm_slabs_in_use"

# fleet observatory (ISSUE 10): cross-host wait attribution + straggler
# detection. The histograms are fed by parallel/multihost.py's instrumented
# barrier/collective wrappers (labels: barrier=<name> / collective=<name>),
# the skew gauge + straggler counter by obs/fleet.py's SkewMonitor, the
# heartbeat gauge at every guarded-barrier entry. Pre-registered so a
# single-host (or skew-free) run reports explicit zeros and
# `mgproto-telemetry fleet` / `check` can always see the series.
BARRIER_WAIT_HIST = "barrier_wait_seconds"
COLLECTIVE_WAIT_HIST = "collective_wait_seconds"
SKEW_GAUGE = "host_step_skew_fraction"
HEARTBEAT_AGE_GAUGE = "peer_heartbeat_age_seconds"
STRAGGLER_COUNTER = "straggler_suspected_total"
ALLGATHER_BYTES_COUNTER = "allgather_bytes_total"
HOST_DEVICES_GAUGE = "host_local_device_count"

# weak-scaling per-chip state (ISSUE 14): what ONE chip holds of the
# class-sharded memory bank and the per-param-sharded optimizer moments
# (planner-measured shape math, perf/planner.py state_bytes_per_chip).
# Pre-registered at zero; set by cli/train at startup and by
# observe_autotune when a plan is chosen, so the fleet table can show
# per-chip memory next to per-chip allgather bytes.
BANK_BYTES_GAUGE = "bank_bytes_per_chip"
OPT_BYTES_GAUGE = "opt_bytes_per_chip"


def _is_primary_host() -> bool:
    from mgproto_tpu.parallel.multihost import is_primary_host

    return is_primary_host()


def resolve_host() -> int:
    """This process's fleet index: jax.process_index() under multi-host, 0
    otherwise (the zero-extra-work single-host path). Best-effort — jax-free
    processes (serving-side tooling, obs/flightrec) resolve to host 0
    instead of failing over identity. The ONE definition; the flight
    recorder shares it."""
    try:
        import jax

        return int(jax.process_index()) if jax.process_count() > 1 else 0
    except Exception:
        return 0


class TelemetrySession:
    def __init__(
        self,
        out_dir: str,
        registry: Optional[MetricRegistry] = None,
        tracer: Optional[Tracer] = None,
        primary: Optional[bool] = None,
        host: Optional[int] = None,
    ):
        self.out_dir = out_dir
        # a FRESH registry/tracer per session (unless the caller brings
        # their own), installed as process-current so classic call sites
        # (timed_span, MetricsWriter mirroring, engine trace_span) route
        # into THIS session — and a second run in the same process starts
        # from zero instead of exporting the first run's totals and spans.
        # close() restores whatever was current before.
        self.registry = registry if registry is not None else MetricRegistry()
        self.tracer = tracer if tracer is not None else Tracer()
        from mgproto_tpu.telemetry.registry import set_current_registry
        from mgproto_tpu.telemetry.tracing import set_current_tracer

        self._prev_registry = set_current_registry(self.registry)
        self._prev_tracer = set_current_tracer(self.tracer)
        self.primary = _is_primary_host() if primary is None else bool(primary)
        # fleet sidecars (ISSUE 10): host 0 owns the canonical unsuffixed
        # artifacts; host p > 0 writes the same streams with a `.h<p>`
        # suffix (run-wide model_dir is shared under multi-host, so they
        # all land in ONE telemetry dir). A session constructed with
        # primary=False and no explicit host (the pre-fleet contract, and
        # any single-process caller) keeps its writers None.
        self.host = resolve_host() if host is None else int(host)
        self.host_suffix = f".h{self.host}" if self.host > 0 else ""
        self._closed = False
        metrics_writer = None
        health_writer = None
        if self.primary or self.host > 0:
            os.makedirs(out_dir, exist_ok=True)
            metrics_writer = JsonlWriter(
                os.path.join(out_dir, METRICS_FILE + self.host_suffix)
            )
            health_writer = JsonlWriter(
                os.path.join(out_dir, HEALTH_FILE + self.host_suffix)
            )
        self._metrics_writer = metrics_writer
        self.monitor = StepMonitor(registry=self.registry)
        self.health = ModelHealth(registry=self.registry, writer=health_writer)
        # pre-register the resilience counter family so a clean run's
        # snapshots carry explicit zeros (summarize then always shows the
        # recovery story, even when it is "nothing happened")
        from mgproto_tpu.resilience.metrics import register_resilience_metrics

        register_resilience_metrics(self.registry)
        # online-learning + drift family (ISSUE 11): same contract — a run
        # that never drifted still snapshots explicit zeros, and the
        # registry lint resolves every online_*/drift_* name here
        from mgproto_tpu.online.metrics import register_online_metrics

        register_online_metrics(self.registry)
        # trust-verification family (ISSUE 15): matrix cells, per-pair
        # AUROC, abstention/accuracy extremes, sharded interp metrics —
        # same explicit-zeros contract as the families above
        from mgproto_tpu.trust.metrics import register_trust_metrics

        register_trust_metrics(self.registry)
        self._g_epoch_ips = self.registry.gauge(
            "epoch_images_per_sec_global",
            "whole-epoch throughput summed across hosts",
        )
        self._g_epoch = self.registry.gauge("epoch", "last completed epoch")
        # EM fast path (pre-registered, see module constants): gauge tracks
        # the widest EM call of the last epoch; the counter accumulates
        # dense-path fallbacks of the compact dirty-class slab
        self._g_em_active = self.registry.gauge(
            EM_ACTIVE_GAUGE,
            "classes EM touched (epoch max of the per-step width)",
        )
        self._g_em_active.set(0.0)
        self._c_em_fallback = self.registry.counter(
            EM_FALLBACK_COUNTER,
            "EM calls that exceeded the compact width and ran the dense "
            "fallback branch",
        )
        self._c_em_fallback.inc(0.0)
        # input pipeline (loader_wait_fraction is also created by the
        # StepMonitor above — this pins the shm-ring gauge, which only the
        # loader's process backend would otherwise create)
        self.registry.gauge(
            DATA_SHM_SLABS_GAUGE,
            "shared-memory batch slabs currently held by in-flight batches",
        ).set(0.0)
        # async bank + auto-tuner: the overlap gauge exists via StepMonitor;
        # pin the planner's rejection counter at an explicit zero
        self._c_autotune_rejected = self.registry.counter(
            AUTOTUNE_REJECTED_COUNTER,
            "auto-tuner candidate plans rejected as over the HBM budget",
        )
        self._c_autotune_rejected.inc(0.0)
        # fleet observatory (ISSUE 10): barrier/collective wait attribution
        # + straggler detection. Histograms are registered name-only (their
        # series appear when a guarded barrier actually runs); the scalars
        # carry explicit zeros so single-host runs report "no skew", not
        # an absent metric.
        self.registry.histogram(
            BARRIER_WAIT_HIST,
            "per-call guarded-barrier wait, labeled barrier=<name>",
        )
        self.registry.histogram(
            COLLECTIVE_WAIT_HIST,
            "per-call host collective wall time (barrier + gather), "
            "labeled collective=<name>",
        )
        self.registry.gauge(
            SKEW_GAUGE,
            "EMA of this host's barrier-arrival skew as a fraction of the "
            "step-time EMA (0 = never the late arriver)",
        ).set(0.0)
        self.registry.gauge(
            HEARTBEAT_AGE_GAUGE,
            "max peer heartbeat age sampled at guarded-barrier entry "
            "(heartbeat decay is visible BEFORE a barrier timeout)",
        ).set(0.0)
        self.registry.counter(
            STRAGGLER_COUNTER,
            "times the skew monitor flagged THIS host as the persistent "
            "last-arriver (each firing arms a targeted profiler capture)",
        ).inc(0.0)
        self.registry.counter(
            ALLGATHER_BYTES_COUNTER,
            "bytes gathered to this host by the instrumented host-side "
            "collectives, labeled collective=<name> (the weak-scaling "
            "per-chip bank/EM traffic deliverable)",
        ).inc(0.0)
        g_dev = self.registry.gauge(
            HOST_DEVICES_GAUGE,
            "devices addressed by this process (per-chip normalizer for "
            "the fleet table)",
        )
        try:
            import jax

            g_dev.set(float(jax.local_device_count()))
        except Exception:
            g_dev.set(1.0)
        # weak-scaling per-chip state gauges (ISSUE 14): explicit zeros
        # until cli/train (or an autotune outcome) measures them
        self._g_bank_bytes = self.registry.gauge(
            BANK_BYTES_GAUGE,
            "bytes of the class-sharded memory bank ONE chip holds "
            "(planner shape math; ~1/model_axis as chips grow)",
        )
        self._g_bank_bytes.set(0.0)
        self._g_opt_bytes = self.registry.gauge(
            OPT_BYTES_GAUGE,
            "bytes of optimizer state (joint+warm+EM-mean Adam moments) "
            "ONE chip holds under the per-param sharding map",
        )
        self._g_opt_bytes.set(0.0)

    def observe_state_bytes(self, per_chip: Dict[str, Any]) -> None:
        """Record the planner's per-chip sharded-state measure
        (perf/planner.py state_bytes_per_chip dict) into the gauges."""
        if per_chip.get("bank_bytes_per_chip") is not None:
            self._g_bank_bytes.set(float(per_chip["bank_bytes_per_chip"]))
        if per_chip.get("opt_bytes_per_chip") is not None:
            self._g_opt_bytes.set(float(per_chip["opt_bytes_per_chip"]))

    def observe_em(self, active_classes: float, compact_fallbacks: float = 0.0):
        """Record one epoch's EM fast-path outcome (host floats — callers
        device_get their metrics first)."""
        self._g_em_active.set(float(active_classes))
        if compact_fallbacks:
            self._c_em_fallback.inc(float(compact_fallbacks))

    def observe_autotune(self, outcome) -> None:
        """Record an HBM auto-tuner run (perf/planner.py PlanOutcome): the
        chosen plan + every candidate's predicted peak land in meta.json
        ("autotune"), rejected candidates increment the counter, and the
        chosen plan's per-chip bank/optimizer bytes land on the gauges."""
        if outcome.rejected:
            self._c_autotune_rejected.inc(float(outcome.rejected))
        if outcome.chosen is not None:
            self.observe_state_bytes(outcome.chosen.to_meta())
        self.write_meta({"autotune": outcome.to_meta()})

    def write_meta(self, meta: Dict[str, Any]) -> None:
        """Persist run configuration context (e.g. prefetch depth, compute
        dtype) as meta.json next to the metric artifacts — primary host
        only; merged over any earlier meta so repeated calls accumulate."""
        if not self.primary or self._closed:
            return
        import json

        path = os.path.join(self.out_dir, META_FILE)
        merged: Dict[str, Any] = {}
        try:
            with open(path) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            pass
        merged.update(meta)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=2, sort_keys=True)
        os.replace(tmp, path)

    def span(self, name: str, **attrs):
        return self.tracer.span(name, **attrs)

    # ------------------------------------------------------------------ sinks
    def flush(self, step: Optional[int] = None, extra: Optional[Dict] = None):
        """Write the current registry + trace state. Host 0 writes the
        canonical files; host p > 0 its `.h<p>` sidecars; a sink-less
        session (primary=False, host 0) writes nothing. Every snapshot
        record carries the host index so merged streams stay attributable."""
        if self._metrics_writer is None or self._closed:
            return
        self.registry.write_prometheus(
            os.path.join(self.out_dir, PROM_FILE + self.host_suffix)
        )
        write_jsonl_snapshot(
            self.registry, self._metrics_writer, step=step,
            extra={"host": self.host, **(extra or {})},
        )
        self.tracer.export_chrome_trace(
            os.path.join(self.out_dir, TRACE_FILE + self.host_suffix)
        )

    def end_epoch(
        self,
        state: Any,
        epoch: int,
        step: Optional[int] = None,
        aggregate: bool = True,
    ) -> Dict[str, float]:
        """Per-epoch bookkeeping: ModelHealth record, global throughput from
        the monitor's epoch accumulators (allgather across hosts when
        `aggregate` — EVERY process must make this call then), flush, and
        reset of the epoch accumulators. Returns the health scalars."""
        local_images = float(self.monitor.epoch_images)
        seconds = self.monitor.epoch_seconds
        if aggregate:
            from mgproto_tpu.parallel.multihost import allgather_sum

            images = allgather_sum(local_images)
        else:
            images = local_images
        if seconds > 0:
            self._g_epoch_ips.set(images / seconds)
        self._g_epoch.set(epoch)
        health = self.health.record(state, epoch=epoch)
        self.flush(step=step, extra={"epoch": int(epoch)})
        self.monitor.begin_epoch()
        return health

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        if self._metrics_writer is not None:
            self._metrics_writer.close()
        if self.health.writer is not None:
            self.health.writer.close()
        self._closed = True
        # restore whatever registry/tracer was current before this session
        from mgproto_tpu.telemetry.registry import set_current_registry
        from mgproto_tpu.telemetry.tracing import set_current_tracer

        set_current_registry(self._prev_registry)
        set_current_tracer(self._prev_tracer)


def make_session(
    telemetry_dir: str, enabled: bool = True, **kw
) -> Optional[TelemetrySession]:
    """`None` when disabled — call sites guard with `if telem:`."""
    if not enabled or not telemetry_dir:
        return None
    return TelemetrySession(telemetry_dir, **kw)
