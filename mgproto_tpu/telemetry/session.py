"""TelemetrySession: one run's telemetry wiring + on-disk artifacts.

Owns (or borrows) a registry and tracer, a StepMonitor and a ModelHealth
recorder, and writes a telemetry directory:

    metrics.prom    latest Prometheus text snapshot (atomic overwrite)
    metrics.jsonl   one registry snapshot per flush (summarize input)
    health.jsonl    one ModelHealth record per epoch
    trace.json      Chrome-trace export of the span tracer

Multi-host: every process computes (SPMD steps and the scalar health
diagnostics need all hosts), but ONLY host 0 sinks to disk — the other
processes keep their writers None, so the artifact set is exactly one
directory per run, not one per host. Cross-host throughput goes through
`parallel.multihost.allgather_sum` in `end_epoch` (every process must call
it: it is a collective).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from mgproto_tpu.telemetry.health import ModelHealth
from mgproto_tpu.telemetry.monitor import StepMonitor
from mgproto_tpu.telemetry.registry import (
    JsonlWriter,
    MetricRegistry,
    write_jsonl_snapshot,
)
from mgproto_tpu.telemetry.tracing import Tracer

PROM_FILE = "metrics.prom"
METRICS_FILE = "metrics.jsonl"
HEALTH_FILE = "health.jsonl"
TRACE_FILE = "trace.json"
META_FILE = "meta.json"

# EM fast-path metrics (core/em.py): pre-registered so a clean run's
# snapshots carry explicit values and `mgproto-telemetry summarize` always
# shows the EM story
EM_ACTIVE_GAUGE = "em_active_classes"
EM_FALLBACK_COUNTER = "em_compact_fallback_total"

# async bank pipeline + HBM auto-tuner (engine/train.py, perf/planner.py):
# the overlap gauge is created by the session's StepMonitor; the rejection
# counter is pre-registered here so a run that never auto-tuned (or whose
# every candidate fit) still reports an explicit zero
BANK_OVERLAP_GAUGE = "bank_dispatch_overlap_fraction"
AUTOTUNE_REJECTED_COUNTER = "autotune_plan_rejected_total"

# input-pipeline metrics (data/loader.py + StepMonitor): pre-registered so
# summarize always shows the data story — a run that never waited on its
# loader (or never used shm slabs) reports explicit zeros
DATA_WAIT_GAUGE = "loader_wait_fraction"
DATA_SHM_SLABS_GAUGE = "loader_shm_slabs_in_use"


def _is_primary_host() -> bool:
    from mgproto_tpu.parallel.multihost import is_primary_host

    return is_primary_host()


class TelemetrySession:
    def __init__(
        self,
        out_dir: str,
        registry: Optional[MetricRegistry] = None,
        tracer: Optional[Tracer] = None,
        primary: Optional[bool] = None,
    ):
        self.out_dir = out_dir
        # a FRESH registry/tracer per session (unless the caller brings
        # their own), installed as process-current so classic call sites
        # (timed_span, MetricsWriter mirroring, engine trace_span) route
        # into THIS session — and a second run in the same process starts
        # from zero instead of exporting the first run's totals and spans.
        # close() restores whatever was current before.
        self.registry = registry if registry is not None else MetricRegistry()
        self.tracer = tracer if tracer is not None else Tracer()
        from mgproto_tpu.telemetry.registry import set_current_registry
        from mgproto_tpu.telemetry.tracing import set_current_tracer

        self._prev_registry = set_current_registry(self.registry)
        self._prev_tracer = set_current_tracer(self.tracer)
        self.primary = _is_primary_host() if primary is None else bool(primary)
        self._closed = False
        metrics_writer = None
        health_writer = None
        if self.primary:
            os.makedirs(out_dir, exist_ok=True)
            metrics_writer = JsonlWriter(os.path.join(out_dir, METRICS_FILE))
            health_writer = JsonlWriter(os.path.join(out_dir, HEALTH_FILE))
        self._metrics_writer = metrics_writer
        self.monitor = StepMonitor(registry=self.registry)
        self.health = ModelHealth(registry=self.registry, writer=health_writer)
        # pre-register the resilience counter family so a clean run's
        # snapshots carry explicit zeros (summarize then always shows the
        # recovery story, even when it is "nothing happened")
        from mgproto_tpu.resilience.metrics import register_resilience_metrics

        register_resilience_metrics(self.registry)
        self._g_epoch_ips = self.registry.gauge(
            "epoch_images_per_sec_global",
            "whole-epoch throughput summed across hosts",
        )
        self._g_epoch = self.registry.gauge("epoch", "last completed epoch")
        # EM fast path (pre-registered, see module constants): gauge tracks
        # the widest EM call of the last epoch; the counter accumulates
        # dense-path fallbacks of the compact dirty-class slab
        self._g_em_active = self.registry.gauge(
            EM_ACTIVE_GAUGE,
            "classes EM touched (epoch max of the per-step width)",
        )
        self._g_em_active.set(0.0)
        self._c_em_fallback = self.registry.counter(
            EM_FALLBACK_COUNTER,
            "EM calls that exceeded the compact width and ran the dense "
            "fallback branch",
        )
        self._c_em_fallback.inc(0.0)
        # input pipeline (loader_wait_fraction is also created by the
        # StepMonitor above — this pins the shm-ring gauge, which only the
        # loader's process backend would otherwise create)
        self.registry.gauge(
            DATA_SHM_SLABS_GAUGE,
            "shared-memory batch slabs currently held by in-flight batches",
        ).set(0.0)
        # async bank + auto-tuner: the overlap gauge exists via StepMonitor;
        # pin the planner's rejection counter at an explicit zero
        self._c_autotune_rejected = self.registry.counter(
            AUTOTUNE_REJECTED_COUNTER,
            "auto-tuner candidate plans rejected as over the HBM budget",
        )
        self._c_autotune_rejected.inc(0.0)

    def observe_em(self, active_classes: float, compact_fallbacks: float = 0.0):
        """Record one epoch's EM fast-path outcome (host floats — callers
        device_get their metrics first)."""
        self._g_em_active.set(float(active_classes))
        if compact_fallbacks:
            self._c_em_fallback.inc(float(compact_fallbacks))

    def observe_autotune(self, outcome) -> None:
        """Record an HBM auto-tuner run (perf/planner.py PlanOutcome): the
        chosen plan + every candidate's predicted peak land in meta.json
        ("autotune"), rejected candidates increment the counter."""
        if outcome.rejected:
            self._c_autotune_rejected.inc(float(outcome.rejected))
        self.write_meta({"autotune": outcome.to_meta()})

    def write_meta(self, meta: Dict[str, Any]) -> None:
        """Persist run configuration context (e.g. prefetch depth, compute
        dtype) as meta.json next to the metric artifacts — primary host
        only; merged over any earlier meta so repeated calls accumulate."""
        if not self.primary or self._closed:
            return
        import json

        path = os.path.join(self.out_dir, META_FILE)
        merged: Dict[str, Any] = {}
        try:
            with open(path) as f:
                merged = json.load(f)
        except (OSError, ValueError):
            pass
        merged.update(meta)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f, indent=2, sort_keys=True)
        os.replace(tmp, path)

    def span(self, name: str, **attrs):
        return self.tracer.span(name, **attrs)

    # ------------------------------------------------------------------ sinks
    def flush(self, step: Optional[int] = None, extra: Optional[Dict] = None):
        """Write the current registry + trace state (primary host only)."""
        if not self.primary or self._closed:
            return
        self.registry.write_prometheus(os.path.join(self.out_dir, PROM_FILE))
        if self._metrics_writer is not None:
            write_jsonl_snapshot(
                self.registry, self._metrics_writer, step=step, extra=extra
            )
        self.tracer.export_chrome_trace(os.path.join(self.out_dir, TRACE_FILE))

    def end_epoch(
        self,
        state: Any,
        epoch: int,
        step: Optional[int] = None,
        aggregate: bool = True,
    ) -> Dict[str, float]:
        """Per-epoch bookkeeping: ModelHealth record, global throughput from
        the monitor's epoch accumulators (allgather across hosts when
        `aggregate` — EVERY process must make this call then), flush, and
        reset of the epoch accumulators. Returns the health scalars."""
        local_images = float(self.monitor.epoch_images)
        seconds = self.monitor.epoch_seconds
        if aggregate:
            from mgproto_tpu.parallel.multihost import allgather_sum

            images = allgather_sum(local_images)
        else:
            images = local_images
        if seconds > 0:
            self._g_epoch_ips.set(images / seconds)
        self._g_epoch.set(epoch)
        health = self.health.record(state, epoch=epoch)
        self.flush(step=step, extra={"epoch": int(epoch)})
        self.monitor.begin_epoch()
        return health

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        if self._metrics_writer is not None:
            self._metrics_writer.close()
        if self.health.writer is not None:
            self.health.writer.close()
        self._closed = True
        # restore whatever registry/tracer was current before this session
        from mgproto_tpu.telemetry.registry import set_current_registry
        from mgproto_tpu.telemetry.tracing import set_current_tracer

        set_current_registry(self._prev_registry)
        set_current_tracer(self._prev_tracer)


def make_session(
    telemetry_dir: str, enabled: bool = True, **kw
) -> Optional[TelemetrySession]:
    """`None` when disabled — call sites guard with `if telem:`."""
    if not enabled or not telemetry_dir:
        return None
    return TelemetrySession(telemetry_dir, **kw)
