"""Telemetry subsystem: metric registry, tracing spans, step/health monitors.

Layering (host-side; nothing here runs on device except the jitted health
diagnostics in `core.em`):

  registry  — process-wide counters/gauges/histograms with labels; JSONL
              snapshot + Prometheus text sinks (`MetricRegistry`).
  tracing   — nesting wall-clock spans with attributes; Chrome-trace JSON
              export (`Tracer`, `trace_span`).
  monitor   — `StepMonitor`: step latency EMA, images/sec, jit cache-miss /
              recompile detection, host-transfer bytes.
  health    — `ModelHealth`: per-epoch EM/prototype diagnostics (prior
              entropy, collapse score, sigma floor, memory occupancy).
  session   — `TelemetrySession`: wires the above to a telemetry directory
              (metrics.prom / metrics.jsonl / health.jsonl / trace.json),
              host-0-only sinks under multi-host.

`cli.telemetry` (the `mgproto-telemetry` subcommand) summarizes a telemetry
directory; `utils.log.Logger` / `MetricsWriter` are thin wrappers over the
same plumbing so pre-telemetry call sites keep working.
"""

from mgproto_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    JsonlWriter,
    MetricRegistry,
    default_registry,
    percentile_from_buckets,
    write_jsonl_snapshot,
)
from mgproto_tpu.telemetry.tracing import Tracer, default_tracer, trace_span
from mgproto_tpu.telemetry.monitor import StepMonitor, tree_transfer_bytes
from mgproto_tpu.telemetry.health import ModelHealth
from mgproto_tpu.telemetry.session import TelemetrySession, make_session

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlWriter",
    "MetricRegistry",
    "default_registry",
    "percentile_from_buckets",
    "write_jsonl_snapshot",
    "Tracer",
    "default_tracer",
    "trace_span",
    "StepMonitor",
    "tree_transfer_bytes",
    "ModelHealth",
    "TelemetrySession",
    "make_session",
]
