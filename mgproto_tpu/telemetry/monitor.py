"""StepMonitor: per-step runtime instrumentation around jitted step calls.

Records into the metric registry, per observed step:

  * `step_time_seconds` histogram + `step_time_ema_seconds` gauge — host
    wall time per step call. jax dispatch is async, so a single interval is
    dispatch time; across an epoch the intervals sum to true wall time
    (the queue must drain), which is what throughput is derived from.
  * `images_per_sec` gauge (EMA-based) + `images_total` / `steps_total`
    counters.
  * `jit_recompiles_total` counter + `jit_cache_size` gauge — cache-miss /
    recompilation detection via `_cache_size()` deltas on the watched
    `jax.jit` functions ("Memory Safe Computations with XLA" (PAPERS.md):
    compiler behavior must be observed, not assumed). The FIRST compile of
    each variant counts too — a steady-state run therefore shows exactly
    its number of compiled variants, and any later growth is a genuine
    shape-driven retrace.
  * `host_transfer_bytes_total` counter — host->device bytes for the step's
    operands (`tree_transfer_bytes` of the batch; the uint8 wire format
    shows up here as a ~4x drop).
  * `loader_wait_fraction` gauge — cumulative fraction of epoch wall time
    the step loop spent blocked fetching the next batch (an input-bound
    epoch reads close to 1; a compute-bound one close to 0).

Compile-time cost analysis (FLOPs / bytes accessed of an AOT-compiled step)
can be attached via `record_cost_analysis` — bench.py uses it so its
telemetry block carries the compiled step's cost next to the measured times.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Iterable, List, Optional, Union

from mgproto_tpu.telemetry.registry import (
    MetricRegistry,
    default_registry,
)

# a jit fn, or a zero-arg provider returning jit fns (re-resolved every
# check, so ShardedTrainer's lazily (re)built jits are picked up)
WatchTarget = Union[Callable, Callable[[], Iterable[Callable]]]


def tree_transfer_bytes(tree: Any) -> int:
    """Total nbytes of the array leaves of a pytree-ish value (host or
    device arrays; anything with .nbytes counts, scalars don't)."""
    total = 0
    stack = [tree]
    while stack:
        x = stack.pop()
        if isinstance(x, (tuple, list)):
            stack.extend(x)
        elif isinstance(x, dict):
            stack.extend(x.values())
        else:
            nbytes = getattr(x, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
    return total


def _cache_size(fn: Callable) -> Optional[int]:
    """Compiled-variant count of a jax.jit callable; None when the wrapper
    (or a plain function) doesn't expose one."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


class StepMonitor:
    """Wraps step calls: `with monitor.step(n_images, batch): ...` or
    explicit `observe_step(n_images, seconds, ...)`."""

    def __init__(
        self,
        registry: Optional[MetricRegistry] = None,
        ema_alpha: float = 0.1,
        phase: str = "train",
    ):
        self.registry = registry if registry is not None else default_registry()
        self.ema_alpha = float(ema_alpha)
        self.phase = phase
        self._watched: List[WatchTarget] = []
        self._last_sizes: dict = {}
        self._ema: Optional[float] = None
        self._epoch_images = 0
        self._epoch_seconds = 0.0
        r = self.registry
        self._h_step = r.histogram(
            "step_time_seconds", "per-step host wall time"
        )
        self._g_ema = r.gauge(
            "step_time_ema_seconds", "EMA of per-step wall time"
        )
        self._g_ips = r.gauge(
            "images_per_sec", "instantaneous throughput (from the step EMA)"
        )
        self._c_steps = r.counter("steps_total", "steps observed")
        self._c_images = r.counter("images_total", "images processed")
        self._c_recompiles = r.counter(
            "jit_recompiles_total",
            "jit cache misses on watched step functions (first compiles "
            "included)",
        )
        self._g_cache = r.gauge(
            "jit_cache_size", "total compiled variants across watched jits"
        )
        self._c_transfer = r.counter(
            "host_transfer_bytes_total", "host->device bytes for step operands"
        )
        self._epoch_wait = 0.0
        self._g_wait_frac = r.gauge(
            "loader_wait_fraction",
            "fraction of epoch wall time the step loop spent blocked on "
            "the input pipeline (batch fetch wait / step time, cumulative "
            "over the epoch)",
        )
        self._g_wait_frac.set(0.0, phase=phase)
        # async bank pipeline (engine/train.py): fraction of epoch wall
        # time a dispatched bank program was in flight concurrently with
        # trunk work — 0.0 exactly when the pipeline is off (sync mode)
        self._epoch_bank_overlap = 0.0
        self._g_bank_overlap = r.gauge(
            "bank_dispatch_overlap_fraction",
            "fraction of epoch wall time the async bank program overlapped "
            "trunk compute (host dispatch-clock estimate; 0 in sync mode)",
        )
        self._g_bank_overlap.set(0.0, phase=phase)
        # compile-time cost gauges (record_cost_analysis below): created
        # here WITHOUT a value so the names are registered (the
        # check_metric_registry lint's contract) while runs that never
        # attach a cost analysis still report "absent", not a fake zero
        self._g_flops = r.gauge(
            "step_flops", "compiled step FLOPs (XLA cost analysis)"
        )
        self._g_bytes = r.gauge(
            "step_bytes_accessed",
            "compiled step bytes accessed (XLA cost analysis)",
        )

    # ------------------------------------------------------------- recompiles
    def watch(self, *targets: WatchTarget) -> "StepMonitor":
        """Watch jit fns (or zero-arg providers of them) for cache growth."""
        self._watched.extend(targets)
        return self

    def _resolve(self) -> List[Callable]:
        fns: List[Callable] = []
        for t in self._watched:
            if _cache_size(t) is not None:
                fns.append(t)
            else:
                try:
                    fns.extend(t())
                except TypeError:
                    fns.append(t)  # un-introspectable fn: counted as size None
        return fns

    def check_recompiles(self) -> int:
        """Cache-size delta across watched jits since the last check;
        increments `jit_recompiles_total` and returns the delta."""
        new = 0
        total = 0
        for fn in self._resolve():
            size = _cache_size(fn)
            if size is None:
                continue
            total += size
            prev = self._last_sizes.get(id(fn), 0)
            if size > prev:
                new += size - prev
            self._last_sizes[id(fn)] = size
        self._g_cache.set(total, phase=self.phase)
        if new:
            self._c_recompiles.inc(new, phase=self.phase)
        return new

    def note_compiles(self, n: int = 1) -> int:
        """Account compiles performed OUTSIDE any watched jit's dispatch
        cache — AOT `lower().compile()` at serving warmup (serving/
        engine.py consults the executable cache and compiles ahead-of-time
        on a miss; the dispatch cache never sees those, so `_cache_size`
        deltas cannot). Keeps `jit_recompiles_total` the one ledger of
        every compile the process performed."""
        if n > 0:
            self._c_recompiles.inc(n, phase=self.phase)
        return n

    @property
    def recompile_count(self) -> int:
        return int(self._c_recompiles.value(phase=self.phase))

    # ------------------------------------------------------------------ steps
    def observe_step(
        self,
        n_images: int,
        seconds: float,
        transfer_bytes: int = 0,
        check_recompiles: bool = True,
        wait_seconds: float = 0.0,
        bank_overlap_seconds: float = 0.0,
    ) -> None:
        ph = self.phase
        self._h_step.observe(seconds, phase=ph)
        self._ema = (
            seconds
            if self._ema is None
            else self.ema_alpha * seconds + (1 - self.ema_alpha) * self._ema
        )
        self._g_ema.set(self._ema, phase=ph)
        if self._ema > 0:
            self._g_ips.set(n_images / self._ema, phase=ph)
        self._c_steps.inc(1, phase=ph)
        self._c_images.inc(n_images, phase=ph)
        if transfer_bytes:
            self._c_transfer.inc(transfer_bytes, phase=ph)
        self._epoch_images += int(n_images)
        self._epoch_seconds += float(seconds)
        self._epoch_wait += float(wait_seconds)
        if self._epoch_seconds > 0:
            self._g_wait_frac.set(
                min(1.0, self._epoch_wait / self._epoch_seconds), phase=ph
            )
        self._epoch_bank_overlap += float(bank_overlap_seconds)
        if self._epoch_seconds > 0:
            self._g_bank_overlap.set(
                min(1.0, self._epoch_bank_overlap / self._epoch_seconds),
                phase=ph,
            )
        if check_recompiles:
            self.check_recompiles()

    @contextlib.contextmanager
    def step(self, n_images: int, batch: Any = None):
        """Time a step call: `with monitor.step(len(images), (images, labels)):
        state, m = trainer.train_step(...)`."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe_step(
                n_images,
                time.perf_counter() - t0,
                transfer_bytes=tree_transfer_bytes(batch) if batch is not None else 0,
            )

    @property
    def ema_seconds(self) -> Optional[float]:
        return self._ema

    # ------------------------------------------------------------------ epoch
    def begin_epoch(self) -> None:
        self._epoch_images = 0
        self._epoch_seconds = 0.0
        self._epoch_wait = 0.0
        self._epoch_bank_overlap = 0.0

    @property
    def epoch_images(self) -> int:
        return self._epoch_images

    @property
    def epoch_seconds(self) -> float:
        return self._epoch_seconds

    @property
    def epoch_wait_seconds(self) -> float:
        return self._epoch_wait

    # ---------------------------------------------------------- cost analysis
    def record_cost_analysis(self, compiled: Any) -> None:
        """Pull FLOPs / bytes-accessed gauges from a compiled module's XLA
        cost analysis (best effort: some PJRT plugins return none)."""
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
        except Exception:
            return
        if not ca:
            return
        flops = ca.get("flops")
        if flops and flops > 0:
            self._g_flops.set(float(flops), phase=self.phase)
        nbytes = ca.get("bytes accessed")
        if nbytes and nbytes > 0:
            self._g_bytes.set(float(nbytes), phase=self.phase)
