"""Hierarchical wall-clock tracing spans with Chrome-trace export.

Supersedes the flat `timed_span` logger line (which now delegates here,
utils/log.py): spans NEST — each records its depth and parent at open time —
carry arbitrary attributes, and the whole recording exports as a Chrome
trace JSON (`chrome://tracing` / Perfetto "traceEvents" format) so a run's
epoch/train/test/push structure is inspectable on a timeline next to the
xprof device trace.

Host-side and jax-free: device work inside a span is measured as the wall
time the host spent dispatching/blocking, exactly like the reference's
epoch timers. The default tracer is process-wide; per-thread span stacks
keep nesting correct under threaded loaders.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class SpanRecord(dict):
    """A completed span: name, ts/dur (seconds since tracer epoch), depth,
    parent index (-1 for roots), tid, attrs. Plain dict subclass so tests
    and exporters can treat records as data."""


class Tracer:
    """Records completed spans; bounded so a forgotten tracer cannot eat the
    host (`dropped` counts what the cap discarded)."""

    def __init__(self, max_spans: int = 200_000):
        self.max_spans = max_spans
        self.dropped = 0
        self._spans: List[SpanRecord] = []
        self._epoch = time.perf_counter()
        self._next_id = 0
        self._lock = threading.Lock()
        self._local = threading.local()

    def _stack(self) -> List[int]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Context manager: records a complete span on exit (exceptions
        included — the span closes and the error propagates). Yields the
        attrs dict so the body can attach results, e.g.
        `with tracer.span("em") as a: a["active"] = n`."""
        stack = self._stack()
        depth = len(stack)
        parent = stack[-1] if stack else -1
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        stack.append(span_id)
        t0 = time.perf_counter()
        try:
            yield attrs
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            rec = SpanRecord(
                id=span_id,
                name=str(name),
                ts=t0 - self._epoch,
                dur=dur,
                depth=depth,
                parent=parent,
                tid=threading.get_ident(),
                attrs={k: _jsonable(v) for k, v in attrs.items()},
            )
            with self._lock:
                if len(self._spans) < self.max_spans:
                    self._spans.append(rec)
                else:
                    self.dropped += 1

    def add_span(
        self,
        name: str,
        ts: float,
        dur: float = 0.0,
        tid: int = 0,
        **attrs,
    ) -> None:
        """Record a span with EXPLICIT timestamps (seconds, in the caller's
        own clock domain) instead of wall-clocking a `with` block. The
        serving-plane request tracer (obs/reqtrace.py) uses this to emit
        per-request stage spans stamped with the plane's injectable clock —
        including the load harness's virtual clock, where perf_counter
        would be meaningless. `dur=0` renders as an instant marker."""
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return
            span_id = self._next_id
            self._next_id += 1
            self._spans.append(SpanRecord(
                id=span_id,
                name=str(name),
                ts=float(ts),
                dur=max(float(dur), 0.0),
                depth=0,
                parent=-1,
                tid=int(tid),
                attrs={k: _jsonable(v) for k, v in attrs.items()},
            ))

    def spans(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # ----------------------------------------------------------------- export
    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome trace-event JSON ('X' complete events, microsecond ts)."""
        pid = os.getpid()
        events = []
        for rec in self.spans():
            events.append({
                "name": rec["name"],
                "ph": "X",
                "ts": rec["ts"] * 1e6,
                "dur": rec["dur"] * 1e6,
                "pid": pid,
                "tid": rec["tid"],
                "args": {**rec["attrs"], "depth": rec["depth"]},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)


def _jsonable(v: Any):
    if isinstance(v, (str, int, float, bool, type(None))):
        return v
    try:
        return float(v)  # device scalars, np numbers
    except (TypeError, ValueError):
        return str(v)


_DEFAULT = Tracer()
_CURRENT = _DEFAULT


def default_tracer() -> Tracer:
    """The process-CURRENT tracer: the process-wide default, or whatever a
    live TelemetrySession installed (sessions install a fresh tracer so a
    second run in the same process doesn't export the first run's spans)."""
    return _CURRENT


def set_current_tracer(tracer: Optional[Tracer]) -> Tracer:
    """Install `tracer` as process-current (None -> the process default);
    returns the previously current tracer so callers can restore it."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = tracer if tracer is not None else _DEFAULT
    return prev


def trace_span(name: str, **attrs):
    """Span on the process-current tracer — the one-liner for engine code;
    routed into the live TelemetrySession's trace when one is active."""
    return _CURRENT.span(name, **attrs)
