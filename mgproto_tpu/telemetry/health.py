"""ModelHealth: per-epoch EM/prototype diagnostics from a TrainState.

MGProto's failure modes are model-health failures before they are loss
failures: prototype collapse (duplicate means), mixture-prior entropy going
to zero (one prototype owns a class), memory banks never filling (EM never
fires), degenerate sigmas. The math lives in `core.em.em_health_diagnostics`
(pure, jittable, returns scalars — so it runs SPMD over any mesh sharding
and the host reads back replicated scalars); this class is the recording
side: gauges in the registry + one JSONL record per call.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Optional

import jax

from mgproto_tpu.core.em import em_health_diagnostics
from mgproto_tpu.telemetry.registry import (
    JsonlWriter,
    MetricRegistry,
    default_registry,
)

_HEALTH_HELP = {
    "prior_entropy_mean": "mean per-class mixture-prior entropy (nats)",
    "prior_entropy_min": "min per-class mixture-prior entropy (nats)",
    "min_interproto_dist": "smallest intra-class inter-prototype distance",
    "collapse_frac": "fraction of intra-class prototype pairs within tol",
    "sigma_floor_frac": "fraction of sigma entries at/below the floor",
    "memory_occupancy": "mean per-class memory-queue fill fraction",
    "memory_full_frac": "fraction of classes with a full memory queue",
    "memory_updated_frac": "fraction of classes touched since last EM",
}


class ModelHealth:
    """Computes + records health diagnostics; `record(state, epoch=...)`
    returns the scalars as plain floats."""

    def __init__(
        self,
        registry: Optional[MetricRegistry] = None,
        writer: Optional[JsonlWriter] = None,
        collapse_tol: float = 1e-3,
        sigma_floor: float = 1e-3,
    ):
        self.registry = registry if registry is not None else default_registry()
        self.writer = writer
        # tolerances are trace-time constants; one compiled diagnostic per
        # (gmm/memory shape) thanks to jit's own cache
        self._diag = jax.jit(
            functools.partial(
                em_health_diagnostics,
                collapse_tol=collapse_tol,
                sigma_floor=sigma_floor,
            )
        )
        self.history: list = []

    def record(
        self, state: Any, epoch: Optional[int] = None, **extra
    ) -> Dict[str, float]:
        vals = jax.device_get(self._diag(state.gmm, state.memory))
        out = {k: float(v) for k, v in vals.items()}
        for k, v in out.items():
            self.registry.gauge(f"model_{k}", _HEALTH_HELP.get(k, "")).set(v)
        rec: Dict[str, Any] = {"time": time.time()}
        if epoch is not None:
            rec["epoch"] = int(epoch)
        rec.update(extra)
        rec.update(out)
        self.history.append(rec)
        if self.writer is not None:
            self.writer.write(rec)
        return out
