"""Process-wide metric registry: counters, gauges, histograms with labels.

The registry is the ONE place run-time scalars accumulate; sinks render it
(Prometheus text for scrapers/humans, JSONL snapshots for the summarize
subcommand). Everything is host-side, jax-free and thread-safe — device
values must be `device_get` floats before they reach a metric.

Design follows the Prometheus data model (the TensorFlow systems paper's
case for built-in metrics, PAPERS.md): a metric has a name, a type, a help
string, and a family of label-keyed series. Histograms use fixed cumulative
buckets so percentile estimates survive snapshot/restore round trips
(`percentile_from_buckets` is shared with `cli/telemetry.py`).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# step latencies span ~1 ms (tiny CPU configs) to minutes (first compile)
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_INVALID_NAME = set(" \t\n{}\",=")


def _check_name(name: str) -> str:
    if not name or _INVALID_NAME & set(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """Base: one named family of label-keyed series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}
        self._lock = threading.Lock()

    def _labels(self) -> Iterable[Tuple[Tuple[Tuple[str, str], ...], Any]]:
        with self._lock:
            return list(self._series.items())


class Counter(_Metric):
    """Monotonically increasing value (resets only with the process)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    """Last-write-wins scalar."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            v = self._series.get(_label_key(labels))
            return None if v is None else float(v)


class _HistSeries:
    __slots__ = ("bucket_counts", "count", "sum", "min", "max")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * (n_buckets + 1)  # +1 for +Inf
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: `le` upper bounds)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ):
        super().__init__(name, help)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = bounds

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.bounds))
            i = 0
            while i < len(self.bounds) and value > self.bounds[i]:
                i += 1
            s.bucket_counts[i] += 1
            s.count += 1
            s.sum += value
            s.min = min(s.min, value)
            s.max = max(s.max, value)

    def snapshot_series(self, **labels) -> Optional[Dict[str, Any]]:
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None:
                return None
            return _hist_dict(self.bounds, s)

    def percentile(self, p: float, **labels) -> Optional[float]:
        snap = self.snapshot_series(**labels)
        if snap is None:
            return None
        return percentile_from_buckets(snap, p)


def _hist_dict(bounds: Sequence[float], s: _HistSeries) -> Dict[str, Any]:
    return {
        "bounds": list(bounds),
        "bucket_counts": list(s.bucket_counts),
        "count": s.count,
        "sum": s.sum,
        "min": None if s.count == 0 else s.min,
        "max": None if s.count == 0 else s.max,
    }


def percentile_from_buckets(hist: Dict[str, Any], p: float) -> Optional[float]:
    """Prometheus-style percentile estimate from a histogram snapshot dict
    ({'bounds', 'bucket_counts', 'count', 'min', 'max'}): linear
    interpolation within the bucket containing the target rank, clamped to
    the observed [min, max] so tiny runs don't report a bucket bound no
    sample ever reached."""
    count = hist.get("count", 0)
    if not count:
        return None
    if not 0.0 <= p <= 100.0:
        raise ValueError(f"percentile must be in [0, 100], got {p}")
    bounds = list(hist["bounds"]) + [math.inf]
    target = p / 100.0 * count
    cum = 0
    for i, n in enumerate(hist["bucket_counts"]):
        prev_cum = cum
        cum += n
        if cum >= target and n > 0:
            lo = bounds[i - 1] if i > 0 else hist.get("min") or 0.0
            hi = bounds[i]
            if math.isinf(hi):
                hi = hist.get("max") or lo
            frac = (target - prev_cum) / n
            est = lo + (hi - lo) * frac
            lo_clamp = hist.get("min")
            hi_clamp = hist.get("max")
            if lo_clamp is not None:
                est = max(est, lo_clamp)
            if hi_clamp is not None:
                est = min(est, hi_clamp)
            return est
    return hist.get("max")


class MetricRegistry:
    """Collection of metrics; `default_registry()` is the process-wide one."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help=help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # ------------------------------------------------------------------ sinks
    def snapshot(self) -> Dict[str, Any]:
        """Nested dict of every metric's current series — the JSONL payload."""
        out: Dict[str, Any] = {}
        for m in self.metrics():
            series = []
            for key, val in m._labels():
                entry: Dict[str, Any] = {"labels": dict(key)}
                if isinstance(m, Histogram):
                    entry.update(_hist_dict(m.bounds, val))
                else:
                    entry["value"] = val
                series.append(entry)
            out[m.name] = {"type": m.kind, "help": m.help, "series": series}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (one scrape's worth)."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, val in m._labels():
                if isinstance(m, Histogram):
                    cum = 0
                    for bound, n in zip(
                        list(m.bounds) + ["+Inf"], val.bucket_counts
                    ):
                        cum += n
                        le = bound if bound == "+Inf" else repr(float(bound))
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_prom_labels(key, extra=('le', le))} {cum}"
                        )
                    lines.append(
                        f"{m.name}_sum{_prom_labels(key)} {_prom_num(val.sum)}"
                    )
                    lines.append(f"{m.name}_count{_prom_labels(key)} {val.count}")
                else:
                    lines.append(f"{m.name}{_prom_labels(key)} {_prom_num(val)}")
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> None:
        """Atomic overwrite (a half-written scrape file is worse than stale)."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)


def _prom_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _prom_labels(
    key: Tuple[Tuple[str, str], ...], extra: Optional[Tuple[str, str]] = None
) -> str:
    items = list(key)
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    def esc(v: str) -> str:
        return str(v).replace("\\", "\\\\").replace('"', '\\"')

    body = ",".join(f'{k}="{esc(v)}"' for k, v in items)
    return "{" + body + "}"


_DEFAULT = MetricRegistry()
_CURRENT = _DEFAULT


def default_registry() -> MetricRegistry:
    """The process-CURRENT registry: the process-wide default, or whatever a
    live TelemetrySession installed (sessions install a fresh registry so a
    second run in the same process starts its counters from zero instead of
    inheriting the first run's totals)."""
    return _CURRENT


def set_current_registry(registry: Optional[MetricRegistry]) -> MetricRegistry:
    """Install `registry` as process-current (None -> the process default);
    returns the previously current registry so callers can restore it."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = registry if registry is not None else _DEFAULT
    return prev


class JsonlWriter:
    """Append-only JSONL file with batched flush+fsync and a closed-guard.

    The shared file core under `MetricsWriter`, the registry snapshot sink
    and the health recorder: one JSON object per `write()`, an OS-level
    flush + fsync every `flush_every` lines (not per line — the seed
    `MetricsWriter` flushed every write, a measurable tax at step cadence),
    and writes after `close()` silently drop (counted in `.dropped`) instead
    of raising on a closed file."""

    def __init__(self, path: Optional[str], flush_every: int = 10):
        self.path = path
        self.flush_every = max(int(flush_every), 1)
        self.dropped = 0
        self._count = 0
        self._f = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a")
    @property
    def closed(self) -> bool:
        return self._f is None

    def write(self, obj: Dict[str, Any]) -> None:
        self.write_line(json.dumps(obj))

    def write_line(self, line: str) -> None:
        """Raw-line variant (Logger's text stream shares this core)."""
        if self._f is None:
            if self.path is not None:
                self.dropped += 1
            return
        self._f.write(line + "\n")
        self._count += 1
        if self._count % self.flush_every == 0:
            self._flush_fsync()

    def _flush_fsync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._flush_fsync()
            self._f.close()
            self._f = None


def write_jsonl_snapshot(
    registry: MetricRegistry,
    writer: JsonlWriter,
    step: Optional[int] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> None:
    """One registry snapshot as one JSONL line (the summarize input)."""
    rec: Dict[str, Any] = {"time": time.time()}
    if step is not None:
        rec["step"] = int(step)
    if extra:
        rec.update(extra)
    rec["metrics"] = registry.snapshot()
    writer.write(rec)
