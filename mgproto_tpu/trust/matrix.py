"""Serving-path robustness matrix: OoD pairs x corruption ladder, gated.

The paper's trust claims are properties of the DEPLOYED decision function —
calibrated thresholds, typed predict/abstain outcomes, pad-to-bucket static
shapes — yet until ISSUE 15 the only OoD evaluation ran through a bespoke
loop (`engine/evaluate.py::evaluate_with_ood`) that never touched the
serving stack. This module drives every matrix cell through the PRODUCTION
`ServingEngine`: warmed buckets, calibration, TrustGate, typed responses,
zero steady-state recompiles (asserted via the engine's StepMonitor).

Cells:
  * one clean-ID cell (the coverage/accuracy anchor + the calibration-drift
    probe: the served log p(x) distribution is compared against the
    calibration's own quantile sketch — a production engine serving the
    calibration's population must sit on its sketch);
  * one cell per ID x OoD dataset pair -> per-pair AUROC over served
    log p(x) (reusing `binary_auroc`) and the abstention contrast (OoD must
    abstain at least as often as ID);
  * one cell per (corruption kind, severity 1..5) — `ops/corrupt.py`
    seeded device-side perturbations of the ID set -> the risk-coverage
    curve: abstention (1 - coverage) must rise monotonically with severity
    while accuracy-on-answered holds above a floor.

Memory stays bounded per chip (the "Memory Safe Computations" discipline):
requests are submitted and drained in bucket-sized chunks, the corruption
programs run on one chunk at a time, and the report carries per-sample
SCALARS only (scores/outcomes), never images.

The emitted `trust_report.json` stores RAW numbers — outcome counts,
correct-on-answered counts, per-sample served scores — next to the derived
rates, so `mgproto-telemetry check --trust` RE-DERIVES every verdict
(cli/telemetry.py::trust_gates) and a tampered rate or AUROC field is
caught against its own raw data.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mgproto_tpu.serving.response import (
    OUTCOME_ABSTAIN,
    OUTCOME_PREDICT,
)
from mgproto_tpu.trust import metrics as _tm
from mgproto_tpu.trust.auroc import binary_auroc

TRUST_REPORT_FORMAT = "mgproto-trust-report-v1"


@dataclasses.dataclass(frozen=True)
class MatrixConfig:
    """Ladder shape + the committed floors the check suite gates against.

    The floors are part of the REPORT (config block) rather than of the
    checker: a matrix run states the bar it was held to, the gate suite
    re-derives the raw numbers and holds them to that same bar, and
    loosening a bar is a reviewed evidence edit, not a code change."""

    kinds: Tuple[str, ...] = ("noise", "blur", "contrast", "pixelate")
    severities: Tuple[int, ...] = (1, 2, 3, 4, 5)
    seed: int = 0
    # verdict floors/tolerances (the config block of the report)
    auroc_floor: float = 0.70  # every ID x OoD pair must separate this well
    answered_accuracy_floor: float = 0.70  # at EVERY severity
    monotone_tol: float = 0.02  # abstention may dip this much between rungs
    px_divergence_limit: float = 0.25  # clean-ID served-vs-calibration drift
    auroc_rederive_tol: float = 1e-9  # recorded vs re-derived (tamper bound)
    score_decimals: int = 5  # stored per-sample score precision


def px_sketch_divergence(scores: np.ndarray, calibration) -> Optional[float]:
    """Mean |served quantile - calibration quantile| over the interior
    sketch points, in calibration-IQR units — the serving-path counterpart
    of online/drift.py's px_divergence (same units, so the drift monitor's
    thresholds transfer). None when there are no scores or the calibration
    sketch is degenerate."""
    s = np.asarray(scores, np.float64).ravel()
    if s.size == 0 or calibration is None:
        return None
    ref = np.asarray(calibration.quantile_log_px, np.float64)
    iqr = ref[75] - ref[25] if ref.size >= 101 else float(np.ptp(ref))
    if not np.isfinite(iqr) or iqr <= 0:
        return None
    qs = np.linspace(0.0, 100.0, ref.size)[1:-1]  # interior points
    served = np.percentile(s, qs)
    return float(np.mean(np.abs(served - ref[1:-1])) / iqr)


def _cell_from_responses(
    responses, labels: Optional[np.ndarray], decimals: int
) -> Dict[str, Any]:
    """Raw per-cell accounting from one cell's typed responses: outcome
    counts, correct-on-answered count, served log p(x) scores of the
    GATED outcomes (predict+abstain — rejects/sheds carry no score)."""
    outcomes: Dict[str, int] = {}
    scores: List[float] = []
    answered = correct = 0
    for i, r in enumerate(responses):
        outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
        if r.log_px is not None:
            scores.append(round(float(r.log_px), decimals))
        if r.outcome == OUTCOME_PREDICT:
            answered += 1
            if labels is not None and r.prediction == int(labels[i]):
                correct += 1
    n = len(responses)
    gated = outcomes.get(OUTCOME_PREDICT, 0) + outcomes.get(
        OUTCOME_ABSTAIN, 0
    )
    return {
        "n": n,
        "outcomes": outcomes,
        "answered": answered,
        "correct_answered": correct if labels is not None else None,
        # derived-for-the-reader values; check re-derives from the raws
        "abstain_rate": (
            outcomes.get(OUTCOME_ABSTAIN, 0) / gated if gated else None
        ),
        "answered_accuracy": (
            correct / answered if (labels is not None and answered) else None
        ),
        "scores": scores,
    }


def serve_cell(
    engine,
    images: np.ndarray,
    labels: Optional[np.ndarray] = None,
    request_prefix: str = "cell",
    deadline_s: Optional[float] = None,
    decimals: int = 5,
) -> Dict[str, Any]:
    """Drive one matrix cell through the engine in bucket-sized chunks
    (bounded host+device memory) and account the typed responses. Every
    submitted request must come back exactly once — the zero-dropped raw
    numbers (`submitted`/`returned`) ride in the cell."""
    chunk = engine.buckets[-1]
    responses = []
    submitted = 0
    for lo in range(0, len(images), chunk):
        part = images[lo : lo + chunk]
        ids = [f"{request_prefix}:{lo + i}" for i in range(len(part))]
        submitted += len(part)
        responses.extend(
            engine.serve_all(list(part), deadline_s=deadline_s,
                             request_ids=ids)
        )
    cell = _cell_from_responses(responses, labels, decimals)
    cell["submitted"] = submitted
    cell["returned"] = len(responses)
    return cell


def run_matrix(
    engine,
    id_images: np.ndarray,
    id_labels: np.ndarray,
    ood_sets: Dict[str, np.ndarray],
    config: MatrixConfig = MatrixConfig(),
    interp: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """The full robustness matrix as one report dict (see module docstring).

    `engine` must be a calibrated ServingEngine; it is warmed here if the
    caller has not already done so. `interp` (optional) merges a sharded
    interpretability result (`trust/interp_sharded.py` /
    `mgproto-trust interp`) into the same report."""
    from mgproto_tpu.ops.corrupt import make_corrupt_fn

    if not engine.warmed_up:
        engine.warmup()
    # steady state begins AFTER warmup: flush any compiles the monitor saw
    engine.monitor.check_recompiles()

    id_images = np.asarray(id_images, np.float32)
    id_labels = np.asarray(id_labels)
    decimals = config.score_decimals

    report: Dict[str, Any] = {
        "trust_report": True,
        "format": TRUST_REPORT_FORMAT,
        "config": {
            "kinds": list(config.kinds),
            "severities": [int(s) for s in config.severities],
            "seed": int(config.seed),
            "auroc_floor": config.auroc_floor,
            "answered_accuracy_floor": config.answered_accuracy_floor,
            "monotone_tol": config.monotone_tol,
            "px_divergence_limit": config.px_divergence_limit,
            "auroc_rederive_tol": config.auroc_rederive_tol,
            "buckets": [int(b) for b in engine.buckets],
            "percentile": (
                engine.gate.calibration.percentile
                if engine.gate.calibration is not None else None
            ),
            "threshold_log_px": (
                engine.gate.threshold
                if engine.gate.calibration is not None else None
            ),
        },
    }

    # ---- clean ID anchor + calibration-drift probe
    id_cell = serve_cell(
        engine, id_images, id_labels, request_prefix="id",
        decimals=decimals,
    )
    id_cell["px_divergence"] = px_sketch_divergence(
        np.asarray(id_cell["scores"]), engine.gate.calibration
    )
    report["id"] = id_cell
    _tm.counter(_tm.MATRIX_CELLS).inc(kind="id")
    _tm.gauge(_tm.ABSTENTION_RATE).set(
        id_cell["abstain_rate"] or 0.0, cell="id:0"
    )
    if id_cell["answered_accuracy"] is not None:
        _tm.gauge(_tm.ANSWERED_ACCURACY).set(
            id_cell["answered_accuracy"], cell="id:0"
        )
    if id_cell["px_divergence"] is not None:
        _tm.gauge(_tm.PX_DIVERGENCE).set(id_cell["px_divergence"])

    # ---- ID x OoD pairs
    pairs = []
    id_scores = np.asarray(id_cell["scores"], np.float64)
    for name in sorted(ood_sets):
        cell = serve_cell(
            engine, np.asarray(ood_sets[name], np.float32), None,
            request_prefix=f"ood:{name}", decimals=decimals,
        )
        cell["pair"] = name
        cell["auroc"] = binary_auroc(
            id_scores, np.asarray(cell["scores"], np.float64)
        )
        pairs.append(cell)
        _tm.counter(_tm.MATRIX_CELLS).inc(kind="ood")
        _tm.gauge(_tm.PAIR_AUROC).set(cell["auroc"], pair=name)
        _tm.gauge(_tm.ABSTENTION_RATE).set(
            cell["abstain_rate"] or 0.0, cell=f"ood:{name}"
        )
    report["pairs"] = pairs

    # ---- corruption ladder (risk-coverage curves per kind)
    from mgproto_tpu.ops.corrupt import per_sample_seeds

    ladder: Dict[str, List[Dict[str, Any]]] = {}
    for kind in config.kinds:
        rows = []
        for severity in config.severities:
            fn = make_corrupt_fn(kind, int(severity))
            corrupted = np.empty_like(id_images)
            chunk = engine.buckets[-1]
            for lo in range(0, len(id_images), chunk):
                part = id_images[lo : lo + chunk]
                seeds = per_sample_seeds(config.seed, len(part), offset=lo)
                corrupted[lo : lo + len(part)] = np.asarray(
                    fn(part, seeds), np.float32
                )
            cell = serve_cell(
                engine, corrupted, id_labels,
                request_prefix=f"{kind}:{severity}", decimals=decimals,
            )
            cell["severity"] = int(severity)
            rows.append(cell)
            _tm.counter(_tm.MATRIX_CELLS).inc(kind=kind)
            _tm.gauge(_tm.ABSTENTION_RATE).set(
                cell["abstain_rate"] or 0.0, cell=f"{kind}:{severity}"
            )
            if cell["answered_accuracy"] is not None:
                _tm.gauge(_tm.ANSWERED_ACCURACY).set(
                    cell["answered_accuracy"], cell=f"{kind}:{severity}"
                )
        ladder[kind] = rows
    report["ladder"] = ladder

    # ---- serving-path invariants, from the engine itself
    report["steady_state_recompiles"] = int(engine.monitor.check_recompiles())
    report["degraded"] = bool(engine.gate.degraded)
    if interp:
        report["interp"] = dict(interp)
        for key, gname in (
            ("consistency", _tm.INTERP_CONSISTENCY),
            ("stability", _tm.INTERP_STABILITY),
            ("purity", _tm.INTERP_PURITY),
        ):
            if isinstance(interp.get(key), (int, float)):
                _tm.gauge(gname).set(float(interp[key]))

    # self-gate for the reader: the SAME derivations check --trust applies
    # (check re-derives from the raw numbers, never trusts this block)
    from mgproto_tpu.cli.telemetry import trust_gates

    report["gates"] = trust_gates(report)
    for row in report["gates"]["rows"]:
        _tm.counter(_tm.VERDICTS).inc(
            result="pass" if row["ok"] else "fail"
        )
    return report
