"""Midrank AUROC — numpy + stdlib only.

The ONE implementation of the threshold-free OoD separability statistic:
AUROC = P(pos > neg) + 0.5 P(pos == neg) via the Mann-Whitney U statistic
on midranks (exact tie handling, no sklearn dependency). It lives here —
not in engine/evaluate.py where it historically sat — because the trust
gate suite (`mgproto-telemetry check --trust`, cli/telemetry.py) must
RE-DERIVE every per-pair AUROC from a committed report's raw scores on a
jax-free host; engine/evaluate.py re-exports it unchanged, so every
existing caller (the bespoke eval loop, the trust matrix, tests) keeps the
same symbol and the two paths cannot drift.
"""

from __future__ import annotations

import numpy as np


def binary_auroc(pos_scores: np.ndarray, neg_scores: np.ndarray) -> float:
    """AUROC = P(pos > neg) + 0.5 P(pos == neg), via the Mann-Whitney U
    statistic on midranks (exact tie handling, no sklearn dependency)."""
    pos = np.asarray(pos_scores, np.float64).ravel()
    neg = np.asarray(neg_scores, np.float64).ravel()
    if not pos.size or not neg.size:
        return float("nan")
    both = np.concatenate([pos, neg])
    order = np.argsort(both, kind="mergesort")
    ranks = np.empty_like(both)
    ranks[order] = np.arange(1, both.size + 1, dtype=np.float64)
    # midranks for ties
    sorted_vals = both[order]
    i = 0
    while i < sorted_vals.size:
        j = i
        while j + 1 < sorted_vals.size and sorted_vals[j + 1] == sorted_vals[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = 0.5 * (i + 1 + j + 1)
        i = j + 1
    u = ranks[: pos.size].sum() - pos.size * (pos.size + 1) / 2.0
    return float(u / (pos.size * neg.size))
