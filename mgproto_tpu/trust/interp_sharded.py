"""Interpretability metrics at scale: the device half sharded over the mesh.

`engine/interpretability.py` evaluates consistency/stability/purity with a
single-device jitted forward (`make_gt_act_fn`) and a host-side geometric
post-pass. At ImageNet-1000 scale (C=1000, P=10 000) the device half — a
full forward plus the [B, C, K, H, W] density tensor and its gt-class
gather — is the bottleneck and does not fit one chip's HBM. This module
lifts exactly that half onto the existing `(data, model)` mesh:

  * the batch shards over 'data' (each chip forwards its rows);
  * the gt-class density gather shard_maps over 'model' EXACTLY like the
    scoring path (`core/mgproto.py::_fused_pool`): each model shard scores
    every patch against its LOCAL [C/nm, K, d] prototype slab only — the
    full density tensor never materializes — selects the rows whose
    ground-truth class it owns, and one psum over 'model' assembles the
    [B, K, h, w] gt map (every other shard contributed exact zeros);
  * the host post-pass is UNCHANGED — the sharded collector returns the
    same (acts, targets, img_ids) triple `evaluate_{consistency,stability,
    purity}` already accept via their `activations=` parameter, so the
    geometry/scoring semantics cannot drift between the two paths.

Parity is pinned in tier-1 (tests/test_trust.py) against the single-device
implementation on the committed `evidence/interp` fixtures: same weights,
same batches, same noise — identical metrics.

Non-divisible shapes (ragged final batch, C % model_axis != 0) fall back
to the single-device activation function for that call, mirroring
`head_forward`'s shard_map divisibility rule.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mgproto_tpu.core.mgproto import GMMState, l2_normalize
from mgproto_tpu.engine.interpretability import (
    collect_gt_activations,
    evaluate_consistency,
    evaluate_purity,
    evaluate_stability,
    make_gt_act_fn,
)
from mgproto_tpu.ops.gaussian import diag_gaussian_log_prob


def make_gt_act_fn_sharded(model, mesh):
    """Sharded counterpart of `make_gt_act_fn`: (params, batch_stats, gmm,
    images, labels) -> [B, K, h, w] exp-density maps of each image's
    gt-class prototypes, with the density + gather shard_mapped over the
    mesh. Shapes must divide the mesh axes (B % data == 0, C % model == 0);
    `sharded_act_fn` wraps this with the fallback rule."""
    from jax.sharding import PartitionSpec as P

    from mgproto_tpu.parallel.mesh import (
        DATA_AXIS,
        MODEL_AXIS,
        shard_map_compat,
    )

    def gather_local(feat, labels, means, sigmas):
        """Per-shard body: feat [B/nd, HW, d] local rows, means/sigmas
        [C/nm, K, d] local class slab. Scores ONLY the local slab, selects
        the rows whose gt class this shard owns, psums the exact-zero
        remainder away."""
        bl, hw, d = feat.shape
        cl, k, _ = means.shape
        lp = diag_gaussian_log_prob(feat.reshape(-1, d), means, sigmas)
        lp = lp.reshape(bl, hw, cl, k)
        base = jax.lax.axis_index(MODEL_AXIS) * cl
        rel = labels - base
        in_shard = (rel >= 0) & (rel < cl)
        sel = jnp.clip(rel, 0, cl - 1)
        picked = jnp.take_along_axis(
            lp, sel[:, None, None, None], axis=2
        )[:, :, 0]  # [B/nd, HW, K]
        picked = jnp.where(in_shard[:, None, None], picked, 0.0)
        return jax.lax.psum(picked, MODEL_AXIS)

    sharded = shard_map_compat(
        gather_local,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(MODEL_AXIS), P(MODEL_AXIS)),
        out_specs=P(DATA_AXIS),
    )

    def fn(params, batch_stats, gmm: GMMState, images, labels):
        variables = {"params": params["net"], "batch_stats": batch_stats}
        proto_map, _ = model.apply(variables, images, train=False)
        b, h, w, d = proto_map.shape
        feat = l2_normalize(proto_map, axis=-1).reshape(b, h * w, d)
        lp_gt = sharded(feat, labels, gmm.means, gmm.sigmas)  # [B, HW, K]
        k = gmm.k_per_class
        return jnp.exp(
            jnp.transpose(lp_gt, (0, 2, 1)).reshape(b, k, h, w)
        )

    return jax.jit(fn)


def sharded_act_fn(trainer):
    """The activation function `collect_gt_activations` should use for
    this trainer: the shard_mapped one on a real mesh with a divisible
    class axis, the single-device one otherwise (plain Trainer, or a
    ragged class count). Batch raggedness is handled per call: the
    returned callable re-routes a non-divisible batch to the single-device
    path for THAT shape only (jit retraces per shape anyway)."""
    mesh = getattr(trainer, "mesh", None)
    single = make_gt_act_fn(trainer.model)
    if mesh is None:
        return single
    from mgproto_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape[MODEL_AXIS]
    if trainer.cfg.model.num_classes % n_model != 0:
        return single
    shard_fn = make_gt_act_fn_sharded(trainer.model, mesh)

    def fn(params, batch_stats, gmm, images, labels):
        if images.shape[0] % (n_data * n_model or 1) == 0 and (
            images.shape[0] % n_data == 0
        ):
            return shard_fn(params, batch_stats, gmm, images, labels)
        return single(params, batch_stats, gmm, images, labels)

    return fn


def collect_gt_activations_sharded(
    trainer,
    state,
    batches,
    use_noise: bool = False,
    noise_seed: int = 0,
):
    """Sharded drop-in for `collect_gt_activations`: same triple, device
    half sharded. The host-side accumulation/validity logic is the
    single-device implementation itself (shared, not copied)."""
    return collect_gt_activations(
        trainer, state, batches,
        use_noise=use_noise, noise_seed=noise_seed,
        act_fn=sharded_act_fn(trainer),
    )


def evaluate_consistency_sharded(
    trainer, state, batches, parts, num_classes: int,
    half_size: int = 36, part_thresh: float = 0.8,
    activations: Optional[Tuple] = None,
) -> float:
    acts = (
        activations
        if activations is not None
        else collect_gt_activations_sharded(trainer, state, batches)
    )
    return evaluate_consistency(
        trainer, state, None, parts, num_classes,
        half_size=half_size, part_thresh=part_thresh, activations=acts,
    )


def evaluate_stability_sharded(
    trainer, state, batches_factory, parts, num_classes: int,
    half_size: int = 36, noise_seed: int = 0,
    activations: Optional[Tuple] = None,
) -> float:
    act_fn = sharded_act_fn(trainer)
    acts = (
        activations
        if activations is not None
        else collect_gt_activations(
            trainer, state, batches_factory(), act_fn=act_fn
        )
    )
    return evaluate_stability(
        trainer, state, batches_factory, parts, num_classes,
        half_size=half_size, noise_seed=noise_seed,
        activations=acts, act_fn=act_fn,
    )


def evaluate_purity_sharded(
    trainer, state, batches, parts, num_classes: int,
    half_size: int = 16, top_k: int = 10,
    activations: Optional[Tuple] = None,
) -> Tuple[float, float]:
    acts = (
        activations
        if activations is not None
        else collect_gt_activations_sharded(trainer, state, batches)
    )
    return evaluate_purity(
        trainer, state, None, parts, num_classes,
        half_size=half_size, top_k=top_k, activations=acts,
    )


def interp_metrics_sharded(
    trainer,
    state,
    batches_factory,
    parts,
    num_classes: int,
    consistency_half_size: int = 36,
    purity_half_size: int = 16,
    part_thresh: float = 0.8,
    top_k: int = 10,
    noise_seed: int = 0,
) -> Dict[str, float]:
    """All three metrics from ONE sharded activation pass over the test
    set (plus the one extra noisy pass stability needs) — the
    `mgproto-trust interp` payload, shaped for `run_matrix(interp=...)`.
    `batches_factory()` returns a fresh (images, labels, img_ids)
    iterator."""
    act_fn = sharded_act_fn(trainer)
    acts = collect_gt_activations(
        trainer, state, batches_factory(), act_fn=act_fn
    )
    consistency = evaluate_consistency(
        trainer, state, None, parts, num_classes,
        half_size=consistency_half_size, part_thresh=part_thresh,
        activations=acts,
    )
    stability = evaluate_stability(
        trainer, state, batches_factory, parts, num_classes,
        half_size=consistency_half_size, noise_seed=noise_seed,
        activations=acts, act_fn=act_fn,
    )
    purity, purity_std = evaluate_purity(
        trainer, state, None, parts, num_classes,
        half_size=purity_half_size, top_k=top_k, activations=acts,
    )
    return {
        "consistency": float(consistency),
        "stability": float(stability),
        "purity": float(purity),
        "purity_std": float(purity_std),
        "num_images": int(np.asarray(acts[1]).shape[0]),
        "sharded": getattr(trainer, "mesh", None) is not None,
    }
