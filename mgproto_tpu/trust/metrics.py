"""Trust-verification metric names and registration (jax-free).

Companion to `serving/metrics.py` / `online/metrics.py`: every trust-plane
event — matrix cells evaluated, per-pair AUROC, per-severity abstention and
answered-accuracy, calibration drift on the served score sketch, sharded
interpretability metric values, verdict outcomes — lands in the telemetry
registry so `mgproto-telemetry summarize` renders the trust story next to
throughput and drift. The whole family is PRE-registered with explicit
zeros (`register_trust_metrics`, called by TelemetrySession) so a run that
never verified still snapshots the series and `check` baselines can gate
them — the repo convention `scripts/check_metric_registry.py` enforces.

Values are rates/scores in [0, 1]-ish units or metric percentages, not
times — no _seconds suffix by design (the unit-convention test allows
_rate/_fraction/score-named gauges).
"""

from __future__ import annotations

from mgproto_tpu.telemetry.registry import Counter, Gauge, default_registry

# robustness matrix (trust/matrix.py)
MATRIX_CELLS = "trust_matrix_cells_total"  # labeled kind= (ood|<corruption>)
PAIR_AUROC = "trust_pair_auroc"  # labeled pair=<ood set>
ABSTENTION_RATE = "trust_abstention_rate"  # labeled cell=<kind:severity>
ANSWERED_ACCURACY = "trust_answered_accuracy"  # labeled cell=
PX_DIVERGENCE = "trust_px_divergence"  # served-vs-calibration sketch drift
VERDICTS = "trust_verdict_total"  # labeled result= pass | fail

# sharded interpretability (trust/interp_sharded.py)
INTERP_CONSISTENCY = "trust_interp_consistency"
INTERP_STABILITY = "trust_interp_stability"
INTERP_PURITY = "trust_interp_purity"

COUNTER_HELP = {
    MATRIX_CELLS:
        "robustness-matrix cells evaluated through the serving path, by "
        "kind (ood pair or corruption family)",
    VERDICTS:
        "trust verdicts derived by the matrix run, by result (pass/fail) "
        "— the same derivations `mgproto-telemetry check --trust` re-runs "
        "from the committed report's raw numbers",
}

GAUGE_HELP = {
    PAIR_AUROC:
        "per ID x OoD pair AUROC of served log p(x) (labeled pair=), "
        "measured through the CALIBRATED serving path, not a bespoke loop",
    ABSTENTION_RATE:
        "abstain fraction of a matrix cell's typed responses (labeled "
        "cell=<kind:severity>; clean ID is cell=id:0)",
    ANSWERED_ACCURACY:
        "accuracy over PREDICT outcomes only of a matrix cell (labeled "
        "cell=) — the risk half of the risk-coverage curve",
    PX_DIVERGENCE:
        "mean |served-quantile - calibration-quantile| of clean-ID "
        "log p(x), in calibration-IQR units (the serving-path counterpart "
        "of drift_px_divergence)",
    INTERP_CONSISTENCY:
        "prototype consistency (%) from the sharded evaluator",
    INTERP_STABILITY:
        "prototype stability (%) from the sharded evaluator",
    INTERP_PURITY:
        "prototype purity mean (%) from the sharded evaluator",
}

ALL_COUNTERS = tuple(COUNTER_HELP)
ALL_GAUGES = tuple(GAUGE_HELP)


def counter(name: str) -> Counter:
    """The named trust counter in the process-current registry."""
    return default_registry().counter(name, COUNTER_HELP.get(name, ""))


def gauge(name: str) -> Gauge:
    """The named trust gauge in the process-current registry."""
    return default_registry().gauge(name, GAUGE_HELP.get(name, ""))


def register_trust_metrics(registry) -> None:
    """Pre-create the trust family with explicit zero-valued unlabeled
    series (the check_metric_registry contract)."""
    for name in ALL_COUNTERS:
        registry.counter(name, COUNTER_HELP[name]).inc(0.0)
    for name in ALL_GAUGES:
        registry.gauge(name, GAUGE_HELP[name]).set(0.0)
