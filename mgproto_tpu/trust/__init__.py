"""Trust verification plane (ISSUE 15, ROADMAP item 5).

Turns the paper's trust claims — prototype consistency/stability/purity and
generative-p(x) OoD detection — into committed, re-derivable regression
gates that run against the PRODUCTION serving path:

  matrix.py         — serving-path robustness matrix: ID x OoD dataset
                      pairs AND a seeded device-side corruption ladder
                      (ops/corrupt.py) driven through a warmed, calibrated
                      ServingEngine; emits one trust_report.json with
                      per-cell AUROC, per-severity risk-coverage curves and
                      calibration-drift readings, gated by
                      `mgproto-telemetry check --trust`.
  interp_sharded.py — consistency/stability/purity device halves lifted
                      into batched/jitted evaluators sharded over the
                      (data, model) mesh, parity-pinned against the
                      single-device implementations.
  auroc.py          — the midrank AUROC statistic, numpy-only so the
                      jax-free check CLI can RE-DERIVE every per-pair
                      verdict from the report's raw scores.
  metrics.py        — the trust_* telemetry family (pre-registered by
                      every TelemetrySession).

Submodules import lazily — `mgproto_tpu.trust.auroc` and `.metrics` stay
importable on a jax-free host (the check/summarize CLI contract).
"""
