"""Hermetic CPU pinning for tests and driver dry runs.

Single home for the relay workaround shared by `tests/conftest.py` and
`__graft_entry__.dryrun_multichip`: steer jax to an n-device virtual CPU
backend and away from the remote TPU relay, BEFORE the first backend init.

Why each knob (see tests/conftest.py for the fuller story):
  * PALLAS_AXON_POOL_IPS="" — the axon sitecustomize registers a remote TPU
    PJRT plugin in every python process when this is set; a wedged relay then
    hangs the backend handshake. Clearing it here is belt-and-braces (the
    sitecustomize runs at interpreter startup, before any of our code).
  * JAX_PLATFORMS=cpu + jax.config.update — steer an already-imported jax to
    the CPU backend.
  * --xla_force_host_platform_device_count=n — fake an n-device mesh on one
    host (SURVEY.md §4's "multi-node without a cluster" story).

This module must stay importable without jax side effects: it imports only
`os` at module level; jax is touched lazily inside the function.
"""

from __future__ import annotations

import os
import re


def pin_cpu_devices(n_devices: int) -> None:
    """Pin this process to a >= n_devices virtual CPU backend.

    Safe to call more than once (a smaller existing device-count flag is
    rewritten in place). NOTE: env rewrites are no-ops once the backend has
    initialized — callers that must be certain follow up with
    `assert_cpu_devices(n_devices)`.
    """
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={n_devices}"
        )

    import jax

    jax.config.update("jax_platforms", "cpu")


def assert_cpu_devices(n_devices: int) -> None:
    """Fail fast (clearly) if the pin did not take effect — e.g. the backend
    was already initialized on another platform before pin_cpu_devices ran."""
    import jax

    devices = jax.devices()
    platform = devices[0].platform if devices else "none"
    assert platform == "cpu" and len(devices) >= n_devices, (
        f"hermetic CPU pin failed: platform={platform}, "
        f"n_devices={len(devices)} (need >= {n_devices} cpu) — the jax "
        "backend was initialized before pin_cpu_devices() could take effect"
    )
