"""EpochGuard: the per-epoch recovery policy around the jitted train step.

Glues together the three host-side halves of fault tolerance:

  * divergence policy — the jitted step already SKIPS non-finite updates
    (engine/train.py `_step` gates every state mutation on a finiteness
    check under lax.cond) and reports a `nonfinite` flag in TrainMetrics.
    The guard accumulates a consecutive-bad-step streak ON DEVICE (lazy
    jnp ops, same pattern as train_epoch's em_active max — no per-step host
    sync) and polls it every `check_every` steps; a streak of
    `max_bad_steps` raises `DivergenceError`, which the training driver
    answers by restoring the last good checkpoint and replaying.
  * preemption — checks the process preemption flag after each completed
    step (multi-host: agreement via `requested_any_host`, same cadence on
    every process) and stops the epoch so the driver can checkpoint.
  * chaos — applies the active ChaosState's batch corruption / simulated
    preemption, keyed by global step, before batches reach the device.

The guard is cheap enough to leave on by default: per step it dispatches
two tiny jnp ops and one python branch; device syncs happen only at the
`check_every` cadence and epoch boundaries.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from mgproto_tpu.resilience import metrics as _metrics
from mgproto_tpu.resilience.chaos import ChaosState
from mgproto_tpu.resilience.preemption import PreemptionHandler


class DivergenceError(RuntimeError):
    """K consecutive non-finite steps: the run should roll back."""

    def __init__(self, streak: int, step: int, epoch: int):
        super().__init__(
            f"{streak} consecutive non-finite train steps at step {step} "
            f"(epoch {epoch}); rolling back to the last good checkpoint"
        )
        self.streak = streak
        self.step = step
        self.epoch = epoch


class EpochGuard:
    """One epoch's worth of recovery policy (construct fresh per epoch).

    Args:
      max_bad_steps: consecutive non-finite steps before DivergenceError
        (0 disables the divergence policy; skipped-step counting remains).
      check_every: host-sync cadence (steps) for the streak poll and the
        multi-host preemption agreement.
      chaos: active ChaosState or None.
      preemption: PreemptionHandler (None disables preemption checks).
      already_done: batches of this epoch completed by a PREVIOUS
        invocation (mid-epoch resume) — `batches_done` counts from here so
        preemption metadata stays an absolute position within the epoch.
      multihost: synchronize the preemption stop across processes.
    """

    def __init__(
        self,
        max_bad_steps: int = 3,
        check_every: int = 8,
        chaos: Optional[ChaosState] = None,
        preemption: Optional[PreemptionHandler] = None,
        already_done: int = 0,
        multihost: bool = False,
    ):
        self.max_bad_steps = int(max_bad_steps)
        self.check_every = max(int(check_every), 1)
        self.chaos = chaos
        self.preemption = preemption
        self.already_done = int(already_done)
        self.multihost = multihost
        self.epoch = -1
        self.preempted = False
        self._base_step = 0
        self._steps = 0
        self._streak = None
        self._bad_total = None
        self._flushed_bad = 0

    # ------------------------------------------------------------- lifecycle
    def begin_epoch(self, epoch: int, state) -> None:
        self.epoch = int(epoch)
        # one host sync per epoch: the global step this epoch starts from
        # (chaos events key on absolute step indices)
        self._base_step = int(jax.device_get(state.step))
        self._steps = 0
        self._streak = jnp.zeros((), jnp.int32)
        self._bad_total = jnp.zeros((), jnp.int32)
        self._flushed_bad = 0
        self.preempted = False

    @property
    def batches_done(self) -> int:
        """Absolute batch position within the epoch (resume metadata)."""
        return self.already_done + self._steps

    # --------------------------------------------------------------- batches
    def wrap_batches(self, batches):
        """Chaos hook on the host batch stream (before device placement).
        Note batches are drawn AHEAD of their step by the prefetch depth, so
        chaos keyed on a batch's step index may raise the preemption flag a
        couple of steps early — harmless: preemption is asynchronous by
        nature and the checkpoint is taken after whichever step last
        finished."""
        if self.chaos is None:
            return batches

        def _gen():
            for i, batch in enumerate(batches):
                global_step = self._base_step + i
                # multi-host pod faults (ISSUE 9): the victim process dies
                # or wedges HERE, before the batch reaches the device, so
                # survivors' next guarded collective times out instead of a
                # device collective deadlocking (the barrier is host-side;
                # see EpochGuard.after_step's check ordering)
                pid = jax.process_index()
                if self.chaos.host_kill_due(global_step, pid):
                    import os

                    from mgproto_tpu.resilience.chaos import (
                        HOST_KILL_EXIT_CODE,
                    )

                    os._exit(HOST_KILL_EXIT_CODE)  # a crash, not a shutdown
                if self.chaos.host_wedge_due(global_step, pid):
                    import time

                    while True:  # a stuck host: alive, silent, not stepping
                        time.sleep(3600)
                slow_s = self.chaos.host_slow_s(global_step, pid)
                if slow_s > 0.0:
                    # non-fatal straggler (ISSUE 10): this host limps
                    # behind every step — the fleet skew monitor, not the
                    # barrier timeout, must be what names it
                    import time

                    time.sleep(slow_s)
                if self.chaos.preempt_due(global_step) and (
                    self.preemption is not None
                ):
                    self.preemption.request("chaos preempt_at_step")
                # batch is (images, labels[, seeds]) — corrupt the images,
                # pass the rest through untouched
                images = self.chaos.corrupt_batch(global_step, batch[0])
                yield (images,) + tuple(batch[1:])

        return _gen()

    # ----------------------------------------------------------------- steps
    def after_step(self, state, train_metrics) -> bool:
        """Observe one completed step; True => stop the epoch (preemption).
        Raises DivergenceError when the bad-step streak crosses the limit."""
        self._steps += 1
        nf = train_metrics.nonfinite.astype(jnp.int32)  # device, lazy
        self._streak = jnp.where(nf > 0, self._streak + 1, 0)
        self._bad_total = self._bad_total + nf

        if self._steps % self.check_every == 0:
            if self.multihost:
                # ORDER is load-bearing under multi-host: the preemption
                # agreement routes through the guarded barrier (pure
                # host-side file IO that can TIME OUT on a dead peer),
                # while the streak poll device_gets a step output — which,
                # with a peer gone, blocks in the step's cross-host
                # collective forever. Checking agreement first gives the
                # barrier its chance to convert a dead/wedged peer into
                # BarrierTimeoutError before anything syncs the device.
                if self._check_preempt():
                    self.preempted = True
                    return True
                self._poll_streak()
            else:
                # single host: divergence takes precedence over preemption
                # (a rollback anchors first; the flag survives the replay)
                self._poll_streak()
                if self._check_preempt():
                    self.preempted = True
                    return True
        elif self.preemption is not None and not self.multihost:
            # single-host preemption costs nothing to check every step
            if self.preemption.requested():
                self.preempted = True
                return True
        return False

    def end_epoch(self) -> int:
        """Flush the skipped-step count to telemetry; final streak check;
        final preemption check (under multihost the per-step checks only run
        at the check_every cadence, so an epoch shorter than check_every —
        or a signal landing in its tail — would otherwise slip through the
        whole next epoch; every process reaches this point after the same
        number of steps, so the agreement collective stays aligned).
        Returns the number of skipped (non-finite) steps this epoch."""
        if self._bad_total is None:
            return 0
        if not self.preempted:
            if self.multihost:
                # agreement before device sync, as in after_step: the
                # barrier must get its timeout chance before _poll_streak/
                # _flush_bad block on a collective a dead peer never joins
                if self._check_preempt():
                    self.preempted = True
                self._poll_streak()
            else:
                self._poll_streak()
                if self._check_preempt():
                    self.preempted = True
        return self._flush_bad()

    # ------------------------------------------------------------- internals
    def _flush_bad(self) -> int:
        total = int(jax.device_get(self._bad_total))
        delta = total - self._flushed_bad
        if delta > 0:
            _metrics.counter(_metrics.SKIPPED_STEPS).inc(delta)
            self._flushed_bad = total
        return total

    def _poll_streak(self) -> None:
        if self.max_bad_steps <= 0:
            return
        streak = int(jax.device_get(self._streak))
        if streak >= self.max_bad_steps:
            self._flush_bad()
            from mgproto_tpu.obs.flightrec import record_event

            # the flight recorder's ring (recent steps, chaos injections)
            # is about to be dumped by the driver's rollback path; the
            # divergence event itself must be ON it
            record_event(
                "divergence", streak=streak, epoch=self.epoch,
                step=self._base_step + self.batches_done - self.already_done,
            )
            raise DivergenceError(
                streak=streak,
                step=self._base_step + self.batches_done - self.already_done,
                epoch=self.epoch,
            )

    def _check_preempt(self) -> bool:
        if self.preemption is None:
            return False
        if self.multihost:
            return self.preemption.requested_any_host()
        return self.preemption.requested()
