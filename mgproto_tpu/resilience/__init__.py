"""Fault-tolerant training subsystem.

Modules (import layering matters — loader spawn workers import this package
and must never pull in jax):

  retry      — generic retry/backoff/jitter (jax-free), used by checkpoint
               IO, `jax.distributed` bring-up, and the loader.
  chaos      — deterministic fault injection (jax-free): loader IO errors,
               NaN losses, checkpoint write failures, simulated preemption.
  preemption — SIGTERM/SIGINT flag + marker file; `install_handlers()` is
               the ONE place allowed to install signal handlers.
  metrics    — resilience counter names + registration (jax-free).
  guard      — `EpochGuard`/`DivergenceError` (imports jax; loaded lazily
               through `__getattr__` so the package import stays jax-free).

See README "Fault tolerance" for the operator-facing story.
"""

from mgproto_tpu.resilience import chaos, metrics, preemption, retry
from mgproto_tpu.resilience.chaos import ChaosPlan, ChaosState
from mgproto_tpu.resilience.preemption import (
    PreemptionHandler,
    get_handler,
    install_handlers,
)
from mgproto_tpu.resilience.retry import retry_call, retryable

_LAZY = ("EpochGuard", "DivergenceError")


def __getattr__(name):
    if name in _LAZY:  # guard imports jax; keep the package import light
        from mgproto_tpu.resilience import guard

        return getattr(guard, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "chaos",
    "metrics",
    "preemption",
    "retry",
    "ChaosPlan",
    "ChaosState",
    "PreemptionHandler",
    "get_handler",
    "install_handlers",
    "retry_call",
    "retryable",
    "EpochGuard",
    "DivergenceError",
]
