"""Generic retry with exponential backoff + jitter (jax-free).

One retry implementation for every transient-failure site — checkpoint IO
(`utils/checkpoint.py`), `jax.distributed` bring-up (`parallel/mesh.py`),
sample loading (`data/loader.py` uses the same delay schedule) — so backoff
behavior and telemetry accounting cannot drift between them. Each performed
retry increments `resilience_retries_total{scope=...}`.

Usable as a callable (`retry_call`) or a decorator (`retryable`). Jitter can
be made deterministic by passing a seeded `numpy` Generator — the chaos
tests rely on this to keep fault-injected runs reproducible.
"""

from __future__ import annotations

import random
import time
from functools import wraps
from typing import Callable, Optional, Tuple, Type


def backoff_delays(
    retries: int,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
    rng=None,
):
    """The delay schedule retry_call sleeps through: base * 2^k, capped at
    max_delay, each scaled by a uniform jitter in [1, 1 + jitter)."""
    for attempt in range(retries):
        delay = min(max_delay, base_delay * (2.0 ** attempt))
        u = rng.random() if rng is not None else random.random()
        yield delay * (1.0 + jitter * u)


def retry_call(
    fn: Callable,
    *args,
    retries: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
    deadline_s: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    no_retry_on: Tuple[Type[BaseException], ...] = (),
    scope: str = "generic",
    on_retry: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng=None,
    **kwargs,
):
    """Call `fn(*args, **kwargs)`, retrying up to `retries` times on
    `retry_on` with exponential backoff (base_delay * 2^k, capped at
    max_delay, jittered). `deadline_s` bounds TOTAL wall time: a retry whose
    backoff would land past the deadline re-raises instead of sleeping.
    `no_retry_on` carves exceptions OUT of `retry_on` — failures that
    retrying can only make worse (a barrier timeout already burned its full
    window reaching failure agreement; re-running it would stall the exit
    the pod launcher is waiting on). `on_retry(attempt, exc, delay)`
    observes each performed retry."""
    start = time.monotonic()
    delays = backoff_delays(retries, base_delay, max_delay, jitter, rng=rng)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if no_retry_on and isinstance(e, no_retry_on):
                raise
            attempt += 1
            if attempt > retries:
                raise
            delay = next(delays)
            if deadline_s is not None and (
                time.monotonic() - start + delay > deadline_s
            ):
                raise
            from mgproto_tpu.resilience import metrics as _m

            _m.counter(_m.RETRIES).inc(scope=scope)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)


def retryable(
    retries: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
    deadline_s: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    no_retry_on: Tuple[Type[BaseException], ...] = (),
    scope: str = "generic",
    on_retry: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Decorator form of `retry_call` (same parameters)."""

    def deco(fn):
        @wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(
                fn,
                *args,
                retries=retries,
                base_delay=base_delay,
                max_delay=max_delay,
                jitter=jitter,
                deadline_s=deadline_s,
                retry_on=retry_on,
                no_retry_on=no_retry_on,
                scope=scope,
                on_retry=on_retry,
                sleep=sleep,
                **kwargs,
            )

        return wrapper

    return deco
