"""Deterministic chaos-injection harness (jax-free).

Injects the faults a preemptible-fleet run actually sees — loader IO
errors, a NaN loss, checkpoint write failures, a preemption signal — under
seed control, so tier-1 tests can prove the recovery machinery restores the
EXACT state a clean run reaches (tests/test_chaos_train.py).

Everything is host-side: NaN injection corrupts a batch BEFORE device
placement and preemption raises the same flag a real SIGTERM sets, so the
jitted step program is identical with chaos on or off (no AOT cost, no
purity loss — the guard inside the step is always compiled in).

Injection points consult the process-active `ChaosState`:

  * `data/loader._load_sample`    — `loader_should_fail` (per-sample IOError,
    transient: fails the first `loader_io_fail_attempts` attempts, then
    succeeds, exercising the retry path without changing the final batch);
  * `utils/checkpoint.save_checkpoint` — `checkpoint_should_fail` (IOError
    after the tmp write, before the publishing rename: a simulated
    kill-mid-save);
  * `resilience.guard.EpochGuard`  — `corrupt_batch` (one-shot NaN images at
    a global step) and `preempt_due` (one-shot simulated SIGTERM).

Deterministic by construction: per-sample failures hash (seed, epoch,
index), one-shot events key on the global step counter; one-shot state
lives in the ChaosState object so a rollback replay does not re-inject.

Serving-side injections (ISSUE 3) follow the same discipline — the
ServingEngine consults the active state per request/dispatch:

  * `serve_corrupt_request` — deterministic per request index: replace the
    payload with a MALFORMED object (wrong shape) or NaN-poison it, at the
    configured rates (exercises serving/validate.py's typed rejects);
  * `serve_storm_due`     — requests in the storm window arrive already
    past their deadline (exercises admission-control shedding);
  * `serve_device_error_due` — listed dispatch indices raise a simulated
    device failure (exercises the circuit breaker), each at most once.

CLI runs configure chaos through env knobs (documented in
`mgproto-train --help`): MGPROTO_CHAOS_SEED, MGPROTO_CHAOS_LOADER_IO_RATE,
MGPROTO_CHAOS_LOADER_IO_FAILS, MGPROTO_CHAOS_NAN_AT_STEP,
MGPROTO_CHAOS_PREEMPT_AT_STEP, MGPROTO_CHAOS_CKPT_FAILS, and for serving
MGPROTO_CHAOS_SERVE_MALFORMED_RATE, MGPROTO_CHAOS_SERVE_NAN_RATE,
MGPROTO_CHAOS_SERVE_DEVICE_ERRORS (comma-separated dispatch indices),
MGPROTO_CHAOS_SERVE_STORM_AT, MGPROTO_CHAOS_SERVE_STORM_LEN, and for the
network serving plane (ISSUE 7) MGPROTO_CHAOS_SERVE_REPLICA_KILL_AT,
MGPROTO_CHAOS_SERVE_WEDGE_AT (admitted-request indices that kill/wedge the
replica the request routes to, one-shot each) and
MGPROTO_CHAOS_SERVE_SWAP_BAD_ARTIFACT (poison the first N hot-swap
attempts with a trust-stripped artifact; the swap must fail closed), and
for online learning (ISSUE 11) MGPROTO_CHAOS_ONLINE_POISON_RATE (fraction
of requests replaced with low-p(x) mislabeled junk the trusted-capture
gate must reject), and for multi-tenant serving (ISSUE 17)
MGPROTO_CHAOS_TENANT_STORM_AT (from this request index the drill floods
ONE tenant over its quota — fair-share admission must shed only that
tenant's own tail), MGPROTO_CHAOS_TENANT_BAD_SWAP (poison the first N
tenant-scoped head swaps with a trust-stripped head; that tenant's swap
must fail closed while every other tenant keeps serving), and
MGPROTO_CHAOS_TENANT_POISON_RATE (fraction of the storm tenant's requests
replaced with OoD junk — its drift monitor must breach while quiet
tenants' monitors stay flat).

Multi-host pod faults (ISSUE 9): MGPROTO_CHAOS_KILL_HOST_AT /
MGPROTO_CHAOS_WEDGE_HOST_AT make one PROCESS die hard (os._exit) or hang
when the batch for that global step is drawn — the canonical pod failures
the guarded barrier (parallel/multihost.py) must answer with failure
agreement instead of deadlock; MGPROTO_CHAOS_HOST_INDEX targets a specific
jax.process_index() (-1 = any process whose environment carries the knob —
the two-process harness sets it on the victim only). One-shot each, hooked
in `resilience.guard.EpochGuard.wrap_batches`.

MGPROTO_CHAOS_SLOW_HOST_MS (ISSUE 10) is the non-fatal sibling: the
targeted process sleeps that many milliseconds before EVERY step — a
chaos-wedged STRAGGLER, not a dead host. The guarded barrier keeps
completing (nobody times out), but every peer waits for the victim each
step, which is exactly what the fleet observatory must attribute: the
barrier-wait histograms fill on the FAST hosts, the arrival-skew monitor
names the victim, and the straggler trigger captures a trace on the victim
only. The injection counter fires once (the delay itself repeats).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional, Tuple

import numpy as np


class ChaosError(IOError):
    """The injected fault type (an IOError so real-IO retry paths fire)."""


# the status a chaos-killed process dies with (os._exit — no cleanup, like a
# real crash). Distinct from PEER_LOST_EXIT_CODE: the launcher relaunches on
# BOTH, but a post-mortem must tell the victim from the survivors.
HOST_KILL_EXIT_CODE = 86


@dataclasses.dataclass
class ChaosPlan:
    """What to inject. All fields off by default."""

    seed: int = 0
    # loader: fraction of (epoch, index) sample loads that fail, and how many
    # attempts each chosen sample fails before succeeding (transient faults;
    # >= the loader's retry budget makes them permanent -> sentinel rows)
    loader_io_rate: float = 0.0
    loader_io_fail_attempts: int = 1
    # one-shot: NaN-corrupt the batch whose train step has this global index
    nan_at_step: Optional[int] = None
    # one-shot: simulated SIGTERM just before this global step's batch
    preempt_at_step: Optional[int] = None
    # first N checkpoint writes fail after the tmp write, before the rename
    checkpoint_write_failures: int = 0
    # serving: fraction of requests whose payload is replaced by a
    # malformed object / NaN-poisoned (deterministic per request index)
    serve_malformed_rate: float = 0.0
    serve_nan_rate: float = 0.0
    # serving: dispatch indices that raise a simulated device error (each
    # fires at most once, so a breaker-gated retry of the same work heals)
    serve_device_errors: Tuple[int, ...] = ()
    # serving: requests [storm_at, storm_at + storm_len) arrive with their
    # deadline already expired (a deadline storm for admission control)
    serve_storm_at: Optional[int] = None
    serve_storm_len: int = 0
    # serving plane (ISSUE 7): when admitted request index >= kill_at, the
    # replica that request would route to dies (simulated process death —
    # stops heartbeating AND dispatching; the supervisor detects the stale
    # heartbeat, reroutes its queue, restarts it on backoff). One-shot.
    serve_replica_kill_at: Optional[int] = None
    # same, but the replica WEDGES: present yet unresponsive (a stuck
    # device call). Identical detection path, distinct restart reason.
    serve_wedge_at: Optional[int] = None
    # the first N blue/green swap attempts stage an artifact whose trust
    # data is stripped (an operator pushing an uncalibrated artifact); the
    # swap MUST reject it fail-closed while the old model keeps serving
    serve_swap_bad_artifact: int = 0
    # online learning (ISSUE 11): fraction of requests replaced with
    # low-p(x) MISLABELED junk (deterministic per request index). The
    # trusted-capture gate (online/capture.py) must reject every one —
    # poisoned traffic never reaches the memory banks; the drift drill
    # counts injections and asserts zero were captured.
    online_poison_rate: float = 0.0
    # multi-tenant serving (ISSUE 17): from this request index on, the
    # load drill floods ONE tenant (the storm tenant) over its fair-share
    # quota; admission must shed only that tenant's own tail
    # (tenant_quota), never another tenant's queued work
    tenant_storm_at: Optional[int] = None
    # the first N tenant-scoped head swaps stage a trust-stripped head;
    # that ONE tenant's swap must fail closed (its gate degrades the
    # staged head) while every other tenant keeps serving untouched
    tenant_bad_swap: int = 0
    # fraction of the storm tenant's requests replaced with OoD junk the
    # per-tenant drift monitor must attribute to that tenant alone
    tenant_poison_rate: float = 0.0
    # multi-host pod faults (ISSUE 9): when the batch for this global step
    # is drawn, the targeted process DIES hard (os._exit — a host crash) or
    # WEDGES (hangs mid-loop — a stuck host). Survivors must reach failure
    # agreement through the guarded barrier (parallel/multihost.py) instead
    # of deadlocking in the next collective. One-shot each.
    kill_host_at: Optional[int] = None
    wedge_host_at: Optional[int] = None
    # non-fatal straggler (ISSUE 10): the targeted process sleeps this many
    # milliseconds before every step — the fleet observatory's skew/wait
    # attribution must name it (repeats every step, counter fires once)
    slow_host_ms: float = 0.0
    # which jax.process_index() the kill/wedge/slow targets; -1 = any
    # process whose env carries the knob (the two-process harness sets the
    # knob in the victim's environment only)
    host_index: int = -1

    def any_active(self) -> bool:
        return (
            self.loader_io_rate > 0.0
            or self.nan_at_step is not None
            or self.preempt_at_step is not None
            or self.checkpoint_write_failures > 0
            or self.serve_malformed_rate > 0.0
            or self.serve_nan_rate > 0.0
            or bool(self.serve_device_errors)
            or (self.serve_storm_at is not None and self.serve_storm_len > 0)
            or self.serve_replica_kill_at is not None
            or self.serve_wedge_at is not None
            or self.serve_swap_bad_artifact > 0
            or self.online_poison_rate > 0.0
            or self.tenant_storm_at is not None
            or self.tenant_bad_swap > 0
            or self.tenant_poison_rate > 0.0
            or self.kill_host_at is not None
            or self.wedge_host_at is not None
            or self.slow_host_ms > 0.0
        )


class ChaosState:
    """A plan plus its mutable one-shot bookkeeping (thread-safe).

    One-shot flags live HERE, not in per-run objects: after a divergence
    rollback replays the same steps, an already-fired injection must not
    fire again (that is what lets a chaos run converge to the clean run's
    exact state)."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._nan_fired = False
        self._preempt_fired = False
        self._ckpt_failures_left = int(plan.checkpoint_write_failures)
        self._serve_errors_left = set(
            int(i) for i in plan.serve_device_errors
        )
        self._replica_kill_fired = False
        self._wedge_fired = False
        self._bad_swaps_left = int(plan.serve_swap_bad_artifact)
        self._tenant_storm_counted = False
        self._tenant_bad_swaps_left = int(plan.tenant_bad_swap)
        self._host_kill_fired = False
        self._host_wedge_fired = False
        self._host_slow_counted = False

    def _count(self, kind: str) -> None:
        from mgproto_tpu.obs.flightrec import record_event
        from mgproto_tpu.resilience import metrics as _m

        _m.counter(_m.CHAOS_INJECTIONS).inc(kind=kind)
        # every injected fault lands on the flight recorder too: a
        # post-mortem dump must show the chaos that provoked the failure
        record_event("chaos_injection", fault=kind)

    # ------------------------------------------------------------- loader IO
    def loader_should_fail(
        self, seed: int, epoch: int, index: int, attempt: int
    ) -> bool:
        """Deterministic per (epoch, index): the SAME samples fail on every
        run of the same plan, and fail only for the first
        `loader_io_fail_attempts` attempts."""
        p = self.plan
        if p.loader_io_rate <= 0.0 or index < 0:
            return False
        if attempt >= p.loader_io_fail_attempts:
            return False
        rng = np.random.default_rng([p.seed, 0x10AD, int(epoch), int(index)])
        hit = bool(rng.random() < p.loader_io_rate)
        if hit:
            self._count("loader_io")
        return hit

    # ------------------------------------------------------------- NaN batch
    def corrupt_batch(self, global_step: int, images: np.ndarray):
        """NaN-poison the batch for `nan_at_step` (once).

        The poisoned batch is always float32 — uint8 has no NaN, so under
        the u8 wire format (DataConfig.device_augment) the drill's one
        batch changes the step's input dtype and compiles a second step
        variant. That is a property of the DRILL, not steady state: one
        extra compile per injected NaN, identical numerics (the augment
        tail consumes f32 transparently), and the divergence guard fires
        exactly as on the f32 pipeline."""
        with self._lock:
            due = (
                self.plan.nan_at_step is not None
                and not self._nan_fired
                and int(global_step) == int(self.plan.nan_at_step)
            )
            if due:
                self._nan_fired = True
        if not due:
            return images
        self._count("nan_loss")
        return np.full_like(np.asarray(images, np.float32), np.nan)

    # ------------------------------------------------------------ preemption
    def preempt_due(self, global_step: int) -> bool:
        """True exactly once, when the batch for `preempt_at_step` is drawn."""
        with self._lock:
            due = (
                self.plan.preempt_at_step is not None
                and not self._preempt_fired
                and int(global_step) >= int(self.plan.preempt_at_step)
            )
            if due:
                self._preempt_fired = True
        if due:
            self._count("preempt_signal")
        return due

    # ----------------------------------------------------------- serving path
    def serve_corrupt_request(self, index: int, payload):
        """Deterministically mangle request `index`'s payload: malformed
        (wrong shape — must become a typed validation reject) or NaN-
        poisoned (must become a typed `nonfinite` reject, never reach the
        device). Precedence: malformed wins when both rates hit."""
        p = self.plan
        if p.serve_malformed_rate <= 0.0 and p.serve_nan_rate <= 0.0:
            return payload
        rng = np.random.default_rng([p.seed, 0x5E12, int(index)])
        roll = rng.random()
        if p.serve_malformed_rate > 0.0 and roll < p.serve_malformed_rate:
            self._count("serve_malformed")
            return np.zeros((3, 3), np.float32)  # wrong rank: bad_shape
        if p.serve_nan_rate > 0.0 and roll < (
            p.serve_malformed_rate + p.serve_nan_rate
        ):
            try:
                shape = np.asarray(payload, np.float32).shape
            except (ValueError, TypeError):
                # payload is ALREADY malformed (ragged/non-numeric): pass
                # it through untouched for the validator's typed reject —
                # the injector must never crash the submit path it drills
                return payload
            self._count("serve_nan")
            return np.full(shape, np.nan, np.float32)
        return payload

    def serve_storm_due(self, index: int) -> bool:
        """True for requests inside the deadline-storm window."""
        p = self.plan
        if p.serve_storm_at is None or p.serve_storm_len <= 0:
            return False
        due = p.serve_storm_at <= int(index) < p.serve_storm_at + p.serve_storm_len
        if due:
            self._count("serve_deadline_storm")
        return due

    def serve_replica_kill_due(self, request_index: int) -> bool:
        """True exactly once, when the admitted-request index reaches
        `serve_replica_kill_at`: the supervisor kills the replica this
        request would have routed to (the request itself reroutes)."""
        with self._lock:
            due = (
                self.plan.serve_replica_kill_at is not None
                and not self._replica_kill_fired
                and int(request_index) >= int(self.plan.serve_replica_kill_at)
            )
            if due:
                self._replica_kill_fired = True
        if due:
            self._count("serve_replica_kill")
        return due

    def serve_replica_wedge_due(self, request_index: int) -> bool:
        """True exactly once, when the admitted-request index reaches
        `serve_wedge_at` (replica present but unresponsive)."""
        with self._lock:
            due = (
                self.plan.serve_wedge_at is not None
                and not self._wedge_fired
                and int(request_index) >= int(self.plan.serve_wedge_at)
            )
            if due:
                self._wedge_fired = True
        if due:
            self._count("serve_replica_wedge")
        return due

    def serve_swap_bad_artifact_due(self) -> bool:
        """True for the first `serve_swap_bad_artifact` swap attempts: the
        staged standby loses its trust data and the swap must fail closed."""
        with self._lock:
            if self._bad_swaps_left <= 0:
                return False
            self._bad_swaps_left -= 1
        self._count("serve_swap_bad_artifact")
        return True

    def online_poison_due(self, request_index: int) -> bool:
        """Deterministic per request index: this request's payload becomes
        low-p(x) mislabeled junk the capture gate must refuse (ISSUE 11;
        the drill drives the substitution, this decides + counts it)."""
        p = self.plan
        if p.online_poison_rate <= 0.0:
            return False
        rng = np.random.default_rng([p.seed, 0x0150, int(request_index)])
        hit = bool(rng.random() < p.online_poison_rate)
        if hit:
            self._count("online_poison")
        return hit

    # ---------------------------------------------------------- tenant plane
    def tenant_storm_due(self, request_index: int) -> bool:
        """True for every request from `tenant_storm_at` on: the drill
        redirects that traffic at the storm tenant, flooding it over its
        fair-share quota (the drill's phase structure bounds the window;
        the injection counter fires once)."""
        p = self.plan
        if p.tenant_storm_at is None:
            return False
        due = int(request_index) >= int(p.tenant_storm_at)
        if due:
            with self._lock:
                counted = self._tenant_storm_counted
                self._tenant_storm_counted = True
            if not counted:
                self._count("tenant_storm")
        return due

    def tenant_bad_swap_due(self) -> bool:
        """True for the first `tenant_bad_swap` tenant-scoped head swaps:
        the staged head loses its trust data and that ONE tenant's swap
        must fail closed while every other tenant keeps serving."""
        with self._lock:
            if self._tenant_bad_swaps_left <= 0:
                return False
            self._tenant_bad_swaps_left -= 1
        self._count("tenant_bad_swap")
        return True

    def tenant_poison_due(self, request_index: int) -> bool:
        """Deterministic per request index: the storm tenant's request
        becomes OoD junk whose drift signature must land on THAT tenant's
        monitor only (the drill drives the substitution)."""
        p = self.plan
        if p.tenant_poison_rate <= 0.0:
            return False
        rng = np.random.default_rng([p.seed, 0x7EA7, int(request_index)])
        hit = bool(rng.random() < p.tenant_poison_rate)
        if hit:
            self._count("tenant_poison")
        return hit

    def serve_device_error_due(self, dispatch_index: int) -> bool:
        """True exactly once per listed dispatch index (a breaker-paced
        retry of later work must be able to heal)."""
        if int(dispatch_index) not in self._serve_errors_left:
            return False
        with self._lock:
            if int(dispatch_index) not in self._serve_errors_left:
                return False
            self._serve_errors_left.discard(int(dispatch_index))
        self._count("serve_device_error")
        return True

    # ------------------------------------------------------- multi-host faults
    def _host_fault_due(
        self, fired_attr: str, at: Optional[int], global_step: int,
        process_index: int, kind: str,
    ) -> bool:
        if at is None:
            return False
        if self.plan.host_index >= 0 and process_index != self.plan.host_index:
            return False
        with self._lock:
            if getattr(self, fired_attr) or int(global_step) < int(at):
                return False
            setattr(self, fired_attr, True)
        self._count(kind)
        return True

    def host_kill_due(self, global_step: int, process_index: int) -> bool:
        """True exactly once, on the targeted process, when the batch for
        `kill_host_at` is drawn: the caller (resilience.guard) hard-exits —
        a simulated host crash mid-pod. Survivors reach failure agreement
        via the guarded barrier's timeout."""
        return self._host_fault_due(
            "_host_kill_fired", self.plan.kill_host_at, global_step,
            process_index, "host_kill",
        )

    def host_wedge_due(self, global_step: int, process_index: int) -> bool:
        """Same, but the process WEDGES (hangs without exiting) — a stuck
        host whose heartbeat goes stale while the barrier times out."""
        return self._host_fault_due(
            "_host_wedge_fired", self.plan.wedge_host_at, global_step,
            process_index, "host_wedge",
        )

    def host_slow_s(self, global_step: int, process_index: int) -> float:
        """Per-step straggler delay (seconds) for the targeted process —
        0.0 everywhere else. Unlike kill/wedge this is NOT one-shot (a
        straggler straggles every step); the injection counter fires once
        so the chaos accounting stays bounded."""
        ms = self.plan.slow_host_ms
        if ms <= 0.0:
            return 0.0
        if self.plan.host_index >= 0 and (
            process_index != self.plan.host_index
        ):
            return 0.0
        with self._lock:
            counted = self._host_slow_counted
            self._host_slow_counted = True
        if not counted:
            self._count("host_slow")
        return ms / 1000.0

    # ---------------------------------------------------------- checkpoint IO
    def checkpoint_should_fail(self) -> bool:
        with self._lock:
            if self._ckpt_failures_left <= 0:
                return False
            self._ckpt_failures_left -= 1
        self._count("checkpoint_write")
        return True


_ACTIVE: Optional[ChaosState] = None
_ACTIVE_LOCK = threading.Lock()


def get_active() -> Optional[ChaosState]:
    """The process-active chaos state (None = no chaos, the normal case)."""
    return _ACTIVE


def set_active(state: Optional[ChaosState]) -> Optional[ChaosState]:
    """Install `state` as process-active; returns the previous one."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        prev = _ACTIVE
        _ACTIVE = state
    return prev


def install(plan: ChaosPlan) -> ChaosState:
    """Wrap `plan` in a ChaosState and make it process-active."""
    state = ChaosState(plan)
    set_active(state)
    return state


def plan_from_env(environ=None) -> Optional[ChaosPlan]:
    """Build a plan from MGPROTO_CHAOS_* env knobs; None when none are set
    (so production runs pay zero chaos overhead)."""
    env = os.environ if environ is None else environ

    def _get(name, cast, default):
        raw = env.get(name)
        if raw is None or raw == "":
            return default
        try:
            return cast(raw)
        except ValueError:
            raise ValueError(f"{name}={raw!r} is not a valid {cast.__name__}")

    def _int_list(raw: str) -> Tuple[int, ...]:
        return tuple(int(v) for v in raw.split(",") if v.strip() != "")

    plan = ChaosPlan(
        seed=_get("MGPROTO_CHAOS_SEED", int, 0),
        loader_io_rate=_get("MGPROTO_CHAOS_LOADER_IO_RATE", float, 0.0),
        loader_io_fail_attempts=_get("MGPROTO_CHAOS_LOADER_IO_FAILS", int, 1),
        nan_at_step=_get("MGPROTO_CHAOS_NAN_AT_STEP", int, None),
        preempt_at_step=_get("MGPROTO_CHAOS_PREEMPT_AT_STEP", int, None),
        checkpoint_write_failures=_get("MGPROTO_CHAOS_CKPT_FAILS", int, 0),
        serve_malformed_rate=_get(
            "MGPROTO_CHAOS_SERVE_MALFORMED_RATE", float, 0.0
        ),
        serve_nan_rate=_get("MGPROTO_CHAOS_SERVE_NAN_RATE", float, 0.0),
        serve_device_errors=_get(
            "MGPROTO_CHAOS_SERVE_DEVICE_ERRORS", _int_list, ()
        ),
        serve_storm_at=_get("MGPROTO_CHAOS_SERVE_STORM_AT", int, None),
        serve_storm_len=_get("MGPROTO_CHAOS_SERVE_STORM_LEN", int, 0),
        serve_replica_kill_at=_get(
            "MGPROTO_CHAOS_SERVE_REPLICA_KILL_AT", int, None
        ),
        serve_wedge_at=_get("MGPROTO_CHAOS_SERVE_WEDGE_AT", int, None),
        serve_swap_bad_artifact=_get(
            "MGPROTO_CHAOS_SERVE_SWAP_BAD_ARTIFACT", int, 0
        ),
        online_poison_rate=_get(
            "MGPROTO_CHAOS_ONLINE_POISON_RATE", float, 0.0
        ),
        tenant_storm_at=_get("MGPROTO_CHAOS_TENANT_STORM_AT", int, None),
        tenant_bad_swap=_get("MGPROTO_CHAOS_TENANT_BAD_SWAP", int, 0),
        tenant_poison_rate=_get(
            "MGPROTO_CHAOS_TENANT_POISON_RATE", float, 0.0
        ),
        kill_host_at=_get("MGPROTO_CHAOS_KILL_HOST_AT", int, None),
        wedge_host_at=_get("MGPROTO_CHAOS_WEDGE_HOST_AT", int, None),
        slow_host_ms=_get("MGPROTO_CHAOS_SLOW_HOST_MS", float, 0.0),
        host_index=_get("MGPROTO_CHAOS_HOST_INDEX", int, -1),
    )
    return plan if plan.any_active() else None
