"""Resilience metric names + registration (jax-free).

Every recovery event the resilience subsystem performs — sample-load
retries, sentinel substitutions, worker-pool restarts, skipped non-finite
steps, checkpoint rollbacks, preemption saves, checkpoint write failures,
chaos injections — lands in the telemetry registry as a labeled counter, so
`mgproto-telemetry summarize` reports them next to throughput and health.

Counters are created on first use through `default_registry()` (so they
follow whatever registry the live TelemetrySession installed), and
`register_resilience_metrics` pre-registers the whole family in a session's
registry so a clean run reports explicit zeros instead of absent series.
"""

from __future__ import annotations

from mgproto_tpu.telemetry.registry import Counter, default_registry

RETRIES = "resilience_retries_total"
SENTINEL_ROWS = "loader_sentinel_rows_total"
WORKER_RESTARTS = "loader_worker_restarts_total"
SKIPPED_STEPS = "train_skipped_steps_total"
ROLLBACKS = "train_rollbacks_total"
PREEMPTION_SAVES = "preemption_saves_total"
CKPT_WRITE_FAILURES = "checkpoint_write_failures_total"
CHAOS_INJECTIONS = "chaos_injections_total"
MISSED_BARRIERS = "missed_barriers_total"
PEER_LOST = "peer_lost_total"
ELASTIC_RESTORES = "elastic_restores_total"

HELP = {
    RETRIES: "retry attempts by scope (loader/checkpoint/distributed_init)",
    SENTINEL_ROWS: "samples replaced by sentinel rows after exhausted retries",
    WORKER_RESTARTS: "loader process-pool restarts after a worker hang/death",
    SKIPPED_STEPS: "train steps whose update was skipped (non-finite loss/grads)",
    ROLLBACKS: "restores to the last good checkpoint by the divergence policy",
    PREEMPTION_SAVES: "preemption-triggered checkpoint saves",
    CKPT_WRITE_FAILURES: "failed checkpoint write attempts (retried)",
    CHAOS_INJECTIONS: "faults injected by the chaos harness, by kind",
    MISSED_BARRIERS: "guarded barriers a peer missed past the timeout, by barrier",
    PEER_LOST: "survivor exits after barrier-timeout failure agreement",
    ELASTIC_RESTORES: "sharded restores onto a different chip/host count than the save",
}

ALL_COUNTERS = tuple(HELP)


def counter(name: str) -> Counter:
    """The named resilience counter in the process-current registry."""
    return default_registry().counter(name, HELP.get(name, ""))


def register_resilience_metrics(registry) -> None:
    """Pre-create the whole counter family with an explicit zero-valued
    unlabeled series, so a clean run's snapshots (and summarize) report 0
    recovery events rather than absent metrics."""
    for name in ALL_COUNTERS:
        registry.counter(name, HELP[name]).inc(0.0)
