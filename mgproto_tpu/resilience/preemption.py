"""Preemption handling: signal flag, marker file, graceful-stop plumbing.

Preemptible TPU fleets deliver SIGTERM with a short grace window. The
handler here only SETS A FLAG — the training loop (engine/train.train_epoch
via `resilience.guard.EpochGuard`) checks it between steps, finishes the
in-flight step, checkpoints the full TrainState, writes a marker file, and
exits cleanly; the next invocation with `--resume auto` continues bit-exactly
(mid-epoch position included — checkpoint metadata records `batch_in_epoch`).

Signal handlers are installed ONLY by `install_handlers()`, called by CLI
drivers after argument parsing — never at import time (enforced by
scripts/check_no_signal_handlers.py in tier-1): a library import that
hijacks SIGINT would break every embedding application's Ctrl-C.

The chaos harness raises the same flag (`PreemptionHandler.request`), so
simulated preemption exercises the identical save/resume path a real
SIGTERM takes.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Optional

MARKER_FILE = "PREEMPTED.json"


class PreemptionHandler:
    """Process-wide preemption flag (thread- and signal-safe)."""

    def __init__(self):
        self._event = threading.Event()
        self.reason: Optional[str] = None

    def request(self, reason: str = "requested") -> None:
        # assignment before set(): a checker that sees the flag must see why
        self.reason = reason
        self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()

    def requested_any_host(self) -> bool:
        """Multi-host agreement: True when ANY process has the flag, so every
        host stops after the SAME step and collectives stay aligned. Every
        process must call this at the same cadence (it is a collective);
        degenerates to the local flag on a single process."""
        local = self.requested()
        from mgproto_tpu.parallel.multihost import any_across_hosts

        return any_across_hosts(local)

    def reset(self) -> None:
        """Clear the flag (each run_training invocation starts clean)."""
        self._event.clear()
        self.reason = None


_HANDLER = PreemptionHandler()


def get_handler() -> PreemptionHandler:
    return _HANDLER


def install_handlers(signums=(signal.SIGTERM, signal.SIGINT), handler=None):
    """Install graceful-preemption signal handlers (the ONLY place in the
    codebase allowed to call `signal.signal` — see module docstring).

    First signal: set the flag, let training checkpoint and exit cleanly.
    Second signal of the same kind: restore the previous disposition and
    re-raise it, so a stuck run can still be killed interactively.

    Returns an `uninstall()` callable restoring the previous handlers
    (tests use it; long-lived drivers never need to)."""
    h = handler if handler is not None else _HANDLER
    previous = {}

    def _on_signal(signum, frame):
        if h.requested():  # second signal: give the process back to the user
            prev = previous.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev if callable(prev) or prev in (
                signal.SIG_DFL, signal.SIG_IGN) else signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        h.request(f"signal {signal.Signals(signum).name}")

    for signum in signums:
        previous[signum] = signal.signal(signum, _on_signal)

    def uninstall():
        for signum, prev in previous.items():
            signal.signal(signum, prev)

    return uninstall


# ----------------------------------------------------------------- marker IO
def marker_path(model_dir: str) -> str:
    return os.path.join(model_dir, MARKER_FILE)


def write_marker(model_dir: str, checkpoint_path: str, reason: str = "",
                 extra: Optional[dict] = None) -> str:
    """Record that this run exited via preemption and where to resume from.
    The next invocation surfaces it (and `--resume auto` picks the
    checkpoint up); a completed resume clears it."""
    path = marker_path(model_dir)
    payload = {
        "checkpoint": os.path.abspath(checkpoint_path),
        "reason": reason,
        "time": time.time(),
    }
    if extra:
        payload.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def read_marker(model_dir: str) -> Optional[dict]:
    path = marker_path(model_dir)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def clear_marker(model_dir: str) -> None:
    try:
        os.unlink(marker_path(model_dir))
    except OSError:
        pass
