"""Multi-host (multi-process) helpers.

Under `jax.distributed` each process addresses only its own chips, so two
host-side idioms that are trivial on one host need care:

  * reading back a data-sharded array (`jax.device_get` of a global array
    whose shards live on other hosts raises "not fully addressable") —
    `host_local_rows` extracts exactly the rows this process contributed;
  * computing dataset-level metrics (accuracy, OoD percentiles, push
    candidates) over per-process shards — `allgather_rows` concatenates
    equal-shaped host-local arrays across processes (the loaders guarantee
    equal shapes: every process runs the same number of identically padded
    batches, data/loader.py).

Everything degenerates to a no-op/device_get on a single process. The REAL
branches are exercised in CI by tests/test_multiprocess.py: two coordinated
`jax.distributed` CPU processes (4 virtual devices each) drive allgather,
put_batch, fetch_replicated, a sharded train step, and the loader's
shard_index>0 path end to end.

Failure agreement (ISSUE 9): a bare collective DEADLOCKS every survivor
when one peer dies or wedges — the canonical pod failure mode. The guarded
barrier below (`configure_barrier` + `guarded_barrier`) wraps the host-side
agreement points (`allgather_sum`/`any_across_hosts`, the epoch-end sync,
the sharded-checkpoint commit) with a heartbeat-file/timeout protocol over
the shared model_dir filesystem: every process touches a per-barrier file
and polls for its peers; a peer missing past `timeout_s` makes survivors
dump the flight recorder, write a PEER_LOST marker, and raise
`BarrierTimeoutError`, which the train driver turns into a clean exit with
`PEER_LOST_EXIT_CODE` — scripts/launch_pod.sh's watchdog loop answers that
code (or the marker appearing on the shared FS) by relaunching everyone
from the last committed checkpoint. Unconfigured (or single-process), every
guard call is a no-op, so library users pay nothing.

Wait attribution (ISSUE 10): every guarded barrier and host collective is
TIMED into the process-current registry — `barrier_wait_seconds{barrier=}`
(time from this host's arrival until the last peer shows up),
`collective_wait_seconds{collective=}` (whole-call wall time of
allgather_sum/allgather_rows), `allgather_bytes_total{collective=}` (bytes
gathered to this host — the weak-scaling per-chip traffic deliverable) and
`peer_heartbeat_age_seconds` (max peer heartbeat age sampled at barrier
entry, so heartbeat decay is visible BEFORE a timeout kills the run). A
completed barrier already knows every peer's arrival time for free — the
seq files' arrival stamps (each peer writes its time.time() into its
file; mtime is the fallback) — so per-peer arrival skew is derived there and
handed to the registered skew observer (`set_skew_observer`;
obs/fleet.SkewMonitor), which turns a persistent last-arriver into a
targeted profiler capture. Single process: the existing early returns skip
ALL of it (one process-count check, nothing else).

Reference: none — the reference is single-process (SURVEY.md §2.3); this is
the scaffolding its NCCL/torch.distributed story never grew.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

# ISSUE 10 wait attribution: per-barrier arrival observer (obs/fleet.py's
# SkewMonitor registers here). Called as fn(name, arrivals, wait_s) with
# arrivals = {process_id: arrival wall time} read from the completed
# barrier's seq-file arrival stamps. None = nobody watching (zero extra
# reads).
_SKEW_OBSERVER: Optional[Callable[[str, Dict[int, float], float], None]] = None


def set_skew_observer(
    fn: Optional[Callable[[str, Dict[int, float], float], None]],
) -> Optional[Callable[[str, Dict[int, float], float], None]]:
    """Install the per-barrier arrival-skew observer (None uninstalls);
    returns the previous one so callers can restore it."""
    global _SKEW_OBSERVER
    prev = _SKEW_OBSERVER
    _SKEW_OBSERVER = fn
    return prev


def _observe_collective(name: str, seconds: float, nbytes: int = 0) -> None:
    """Record one host-collective call into the process-current registry
    (collective_wait_seconds + allgather_bytes_total). Only reached on the
    real multi-process branches — single-host pays nothing."""
    from mgproto_tpu.telemetry.registry import default_registry
    from mgproto_tpu.telemetry.session import (
        ALLGATHER_BYTES_COUNTER,
        COLLECTIVE_WAIT_HIST,
    )

    r = default_registry()
    r.histogram(COLLECTIVE_WAIT_HIST).observe(
        float(seconds), collective=name
    )
    if nbytes:
        r.counter(ALLGATHER_BYTES_COUNTER).inc(float(nbytes), collective=name)


def is_primary_host() -> bool:
    """True on the one process that owns run-wide side effects (telemetry
    sinks, checkpoints' metadata): process 0. Single process: True."""
    return jax.process_index() == 0


def host_local_rows(arr: jax.Array) -> np.ndarray:
    """Rows of a leading-axis-sharded global array that live on THIS process,
    in ascending global-row order. Single process: the whole array."""
    if jax.process_count() == 1:
        return np.asarray(jax.device_get(arr))
    by_start = {}
    for s in arr.addressable_shards:
        start = s.index[0].start or 0
        by_start.setdefault(start, np.asarray(s.data))  # dedupe replicas
    return np.concatenate(
        [by_start[k] for k in sorted(by_start)], axis=0
    )


def allgather_rows(x: np.ndarray) -> np.ndarray:
    """Concatenate equal-shaped per-process host arrays across all processes
    (row-major in process order). A host-side agreement collective (the
    per-epoch eval/push gathers ride on it), so it is guarded like
    `allgather_sum`: a dead peer surfaces as `BarrierTimeoutError` instead
    of deadlocking every survivor in the bare collective. Single process:
    identity."""
    if jax.process_count() == 1:
        return x
    t0 = time.monotonic()
    guarded_barrier("allgather_rows")
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(np.asarray(x))
    out = np.concatenate(list(stacked), axis=0)
    _observe_collective("allgather_rows", time.monotonic() - t0, out.nbytes)
    return out


def _f64_to_wire(x: float) -> np.ndarray:
    """Encode a float64 scalar as its 8 raw bytes (uint8). The allgather
    wire dtype is pinned to uint8 because `process_allgather` stages host
    arrays through the device: under the default x32 mode a float64 array
    silently downcasts to float32 on device, so large counters (image
    totals past 2^24) lose exact integer precision. uint8 survives any
    jax dtype policy bit-for-bit."""
    return np.frombuffer(np.float64(x).tobytes(), dtype=np.uint8).copy()


def _f64_from_wire(row: np.ndarray) -> float:
    return float(np.frombuffer(
        np.ascontiguousarray(row, dtype=np.uint8).tobytes(), np.float64
    )[0])


def allgather_sum(x: float) -> float:
    """Sum a host-side scalar across processes (float64 end to end — the
    wire is raw bytes, see `_f64_to_wire`). A host-side agreement
    collective: when a barrier guard is configured it is guarded, so a dead
    peer surfaces as `BarrierTimeoutError` instead of a deadlock. Single
    process: identity."""
    if jax.process_count() == 1:
        return float(x)
    t0 = time.monotonic()
    guarded_barrier("allgather_sum")
    from jax.experimental import multihost_utils

    stacked = np.asarray(multihost_utils.process_allgather(_f64_to_wire(x)))
    out = float(sum(_f64_from_wire(row) for row in stacked))
    _observe_collective("allgather_sum", time.monotonic() - t0, stacked.nbytes)
    return out


def any_across_hosts(flag: bool) -> bool:
    """True when ANY process passes True — the preemption agreement: a
    SIGTERM lands on ONE host, but every host must stop after the SAME step
    or the next collective deadlocks. A collective itself (every process
    must call it at the same cadence; guarded through `allgather_sum` when
    a barrier guard is configured); single process: identity."""
    if jax.process_count() == 1:
        return bool(flag)
    return allgather_sum(1.0 if flag else 0.0) > 0.0


_REPLICATING_JITS: dict = {}


def _replicating_identity(mesh):
    """Per-mesh cached identity jit with replicated out_shardings (jit's own
    cache then handles distinct tree structures) — a fresh lambda per call
    would retrace every push/interpret invocation."""
    fn = _REPLICATING_JITS.get(mesh)
    if fn is None:
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        fn = jax.jit(lambda t: t, out_shardings=rep)
        _REPLICATING_JITS[mesh] = fn
    return fn


def fetch_replicated(tree: Any, mesh=None) -> Any:
    """Host-local numpy copy of a (possibly cross-host-sharded) pytree.

    Sharded leaves are first replicated by an SPMD identity (XLA all-gathers
    over ICI/DCN), making every leaf fully addressable; then device_get.
    Used by host-driven passes (push scan, interpretability) that re-run
    their own local jits over per-process batches."""
    leaves = jax.tree_util.tree_leaves(tree)
    needs_gather = any(
        isinstance(l, jax.Array) and not l.is_fully_addressable for l in leaves
    )
    if needs_gather:
        if mesh is None:
            raise ValueError("fetch_replicated needs the mesh for sharded input")
        tree = _replicating_identity(mesh)(tree)
    return jax.device_get(tree)


# --------------------------------------------------------------------------
# Guarded barrier: failure agreement instead of deadlock (ISSUE 9 tentpole).
# --------------------------------------------------------------------------

PEER_LOST_FILE = "PEER_LOST.json"
# the distinct exit status a survivor leaves with after writing the marker:
# scripts/launch_pod.sh's watchdog loop treats it (or the marker file
# appearing on the shared FS) as "relaunch everyone from the last commit".
# 75 = EX_TEMPFAIL: the run is retryable, the state is safe on disk.
PEER_LOST_EXIT_CODE = 75

BARRIER_SUBDIR = ".barrier"
_HEARTBEAT_PREFIX = "hb.h"


class BarrierTimeoutError(RuntimeError):
    """A peer missed a guarded barrier past the timeout (dead or wedged
    host). Survivors have already dumped the flight recorder and written
    the PEER_LOST marker; the driver should exit PEER_LOST_EXIT_CODE so the
    pod launcher relaunches from the last committed checkpoint."""

    def __init__(self, name: str, missing: List[int], timeout_s: float):
        super().__init__(
            f"barrier {name!r}: processes {missing} missing after "
            f"{timeout_s:.1f}s (dead or wedged peer); survivors exit for "
            "relaunch-from-last-commit"
        )
        self.name = name
        self.missing = missing
        self.timeout_s = timeout_s


@dataclasses.dataclass
class BarrierGuard:
    """File-based barrier + heartbeat state over a shared directory.

    Every process touches `<name>.<seq>.h<pid>` and polls until all
    `num_processes` files of that (name, seq) exist; `seq` is a per-name
    local counter, aligned across processes because the host loop is SPMD
    (every process reaches every guarded call in the same order). Heartbeat
    files (`hb.h<pid>`) are touched at step cadence by the training loop so
    a timeout report can say how stale each missing peer is.

    The barrier directory is namespaced by a per-incarnation session token
    (see `configure_barrier`): a relaunch after a PEER_LOST exit must never
    see the dead incarnation's barrier files — seq counters restart at 0,
    so stale files would satisfy (or corrupt) the new run's barriers."""

    barrier_dir: str
    marker_dir: str
    timeout_s: float
    process_id: int
    num_processes: int
    poll_s: float = 0.05
    heartbeat_min_interval_s: float = 0.5
    _seq: Dict[str, int] = dataclasses.field(default_factory=dict)
    _last_heartbeat: float = 0.0

    def _file(self, name: str, seq: int, pid: int) -> str:
        safe = "".join(c if (c.isalnum() or c in "._-") else "_" for c in name)
        return os.path.join(
            self.barrier_dir, f"{safe}.{seq:06d}.h{pid:05d}"
        )


_BARRIER: Optional[BarrierGuard] = None


def _agree_session_token() -> str:
    """A session token every live process agrees on: host 0's wall clock at
    configure time, broadcast over the device collective (all processes are
    alive at bring-up — that is when this runs). Namespacing the barrier
    directory with it keeps a relaunch from reading the dead incarnation's
    barrier files."""
    from jax.experimental import multihost_utils

    local = np.asarray(
        [time.time_ns() & 0x7FFFFFFFFFFFFFFF], dtype=np.int64
    )
    agreed = multihost_utils.broadcast_one_to_all(local)
    return f"{int(agreed[0]):x}"


def configure_barrier(
    model_dir: str,
    timeout_s: float,
    process_id: Optional[int] = None,
    num_processes: Optional[int] = None,
    poll_s: float = 0.05,
    session: Optional[str] = None,
) -> Optional[BarrierGuard]:
    """Install the process-global barrier guard over `model_dir` (which
    multi-host training already requires to be a shared filesystem — the
    checkpoints live there). `timeout_s <= 0` disables guarding (barriers
    no-op; collectives run bare). `session` names this incarnation's
    barrier subdirectory; by default a real multi-process run agrees on one
    via a broadcast (tests simulating peers pass it explicitly). Returns
    the installed guard (None when disabled)."""
    global _BARRIER
    if timeout_s is None or timeout_s <= 0:
        _BARRIER = None
        return None
    if session is None:
        # MGPROTO_BARRIER_SESSION: a launcher-minted shared incarnation id
        # (the CPU pod harness; a k8s job uid) — skips the bring-up
        # broadcast entirely
        session = os.environ.get("MGPROTO_BARRIER_SESSION") or (
            _agree_session_token()
            if process_id is None and jax.process_count() > 1
            else "s0"
        )
    guard = BarrierGuard(
        barrier_dir=os.path.join(model_dir, BARRIER_SUBDIR, session),
        marker_dir=model_dir,
        timeout_s=float(timeout_s),
        process_id=(
            jax.process_index() if process_id is None else int(process_id)
        ),
        num_processes=(
            jax.process_count() if num_processes is None else int(num_processes)
        ),
        poll_s=poll_s,
    )
    os.makedirs(guard.barrier_dir, exist_ok=True)
    _BARRIER = guard
    return guard


def barrier_guard() -> Optional[BarrierGuard]:
    return _BARRIER


def clear_barrier() -> None:
    """Uninstall the guard (run_training's finally block)."""
    global _BARRIER
    _BARRIER = None


def heartbeat_tick() -> None:
    """Touch this process's heartbeat file (rate-limited). Called from the
    train-step loop and on barrier entry, so a peer's heartbeat age in the
    PEER_LOST diagnosis records WHEN it last made host-loop progress: an
    age near the barrier wait means it was alive until moments before the
    timeout (died or wedged mid-step just now), a much older age means it
    stopped long before, and None means it never reached the loop (lost at
    bring-up). It cannot distinguish dead from wedged — a wedged host's
    loop stops ticking exactly like a dead one's. No-op unless a guard is
    configured."""
    g = _BARRIER
    if g is None:
        return
    now = time.monotonic()
    if now - g._last_heartbeat < g.heartbeat_min_interval_s:
        return
    g._last_heartbeat = now
    path = os.path.join(
        g.barrier_dir, f"{_HEARTBEAT_PREFIX}{g.process_id:05d}"
    )
    try:
        with open(path, "w") as f:
            f.write(str(time.time()))
    except OSError:
        pass  # liveness signal is best-effort; never fail a step over it


def peer_heartbeat_ages() -> Dict[int, Optional[float]]:
    """Seconds since each peer's last heartbeat (None = never seen).
    Diagnostic payload for the PEER_LOST marker."""
    g = _BARRIER
    if g is None:
        return {}
    ages: Dict[int, Optional[float]] = {}
    now = time.time()
    for pid in range(g.num_processes):
        path = os.path.join(g.barrier_dir, f"{_HEARTBEAT_PREFIX}{pid:05d}")
        try:
            ages[pid] = max(0.0, now - os.path.getmtime(path))
        except OSError:
            ages[pid] = None
    return ages


def _on_barrier_timeout(g: BarrierGuard, name: str, missing: List[int]):
    """Survivor path: marker + flight-recorder dump + counter, then raise.
    Imports are local so this module stays cheap for non-failure paths."""
    from mgproto_tpu.obs.flightrec import get_recorder, record_event
    from mgproto_tpu.resilience import metrics as _m

    ages = peer_heartbeat_ages()
    _m.counter(_m.MISSED_BARRIERS).inc(barrier=name)
    _m.counter(_m.PEER_LOST).inc()
    record_event(
        "barrier_timeout", barrier=name, missing=missing,
        heartbeat_ages={str(k): v for k, v in ages.items()},
    )
    marker = os.path.join(g.marker_dir, PEER_LOST_FILE)
    payload = {
        "barrier": name,
        "missing_processes": missing,
        "survivor": g.process_id,
        "timeout_s": g.timeout_s,
        "heartbeat_ages_s": {str(k): v for k, v in ages.items()},
        "time": time.time(),
        "exit_code": PEER_LOST_EXIT_CODE,
    }
    try:
        tmp = marker + f".tmp{g.process_id}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, marker)
    except OSError:
        pass  # the raise below still carries the diagnosis
    get_recorder().maybe_dump("peer_lost")
    raise BarrierTimeoutError(name, missing, g.timeout_s)


def _sample_heartbeat_age(g: BarrierGuard) -> None:
    """Max PEER heartbeat age -> the `peer_heartbeat_age_seconds` gauge,
    sampled at barrier entry (ISSUE 10 satellite): heartbeat decay becomes
    visible in telemetry BEFORE a stale peer turns into a barrier timeout."""
    from mgproto_tpu.telemetry.registry import default_registry
    from mgproto_tpu.telemetry.session import HEARTBEAT_AGE_GAUGE

    ages = [
        a for pid, a in peer_heartbeat_ages().items()
        if pid != g.process_id and a is not None
    ]
    if ages:
        default_registry().gauge(HEARTBEAT_AGE_GAUGE).set(max(ages))


def _observe_barrier_wait(
    g: BarrierGuard, name: str, seq: int, wait_s: float
) -> None:
    """Post-completion accounting: the wait histogram, and — when a skew
    observer is registered — per-peer arrival times from the completed
    barrier's seq files (each peer already recorded WHEN it arrived:
    `guarded_barrier` writes its `time.time()` INTO `<name>.<seq>.h<pid>`,
    so last-arriver identity and skew magnitude come for free; the file's
    mtime is only the fallback — shared-FS mtime granularity can be a full
    second, far coarser than the skews the monitor must resolve).
    Observation must never fail a barrier."""
    from mgproto_tpu.telemetry.registry import default_registry
    from mgproto_tpu.telemetry.session import BARRIER_WAIT_HIST

    default_registry().histogram(BARRIER_WAIT_HIST).observe(
        wait_s, barrier=name
    )
    obs = _SKEW_OBSERVER
    if obs is None:
        return
    arrivals: Dict[int, float] = {}
    for pid in range(g.num_processes):
        path = g._file(name, seq, pid)
        try:
            with open(path) as f:
                arrivals[pid] = float(f.read().strip())
        except (OSError, ValueError):
            try:
                arrivals[pid] = os.path.getmtime(path)
            except OSError:
                pass  # already reaped on a slow observer
    try:
        obs(name, arrivals, wait_s)
    except Exception:
        pass


def guarded_barrier(name: str) -> None:
    """Block until every process reaches this named barrier, or raise
    `BarrierTimeoutError` after `timeout_s` listing the missing peers.
    No-op when unconfigured or effectively single-process. Must be called
    in the same order by every process (SPMD host loop) — same contract as
    the collectives it protects."""
    g = _BARRIER
    if g is None or g.num_processes <= 1:
        return
    seq = g._seq.get(name, 0)
    g._seq[name] = seq + 1
    heartbeat_tick()
    _sample_heartbeat_age(g)
    mine = g._file(name, seq, g.process_id)
    with open(mine, "w") as f:
        f.write(str(time.time()))
    t_arrived = time.monotonic()
    deadline = t_arrived + g.timeout_s
    while True:
        missing = [
            pid for pid in range(g.num_processes)
            if not os.path.exists(g._file(name, seq, pid))
        ]
        if not missing:
            break
        if time.monotonic() > deadline:
            _on_barrier_timeout(g, name, missing)
        time.sleep(g.poll_s)
    _observe_barrier_wait(g, name, seq, time.monotonic() - t_arrived)
    # barrier `seq` completed globally: every peer has SEEN all files of
    # this seq, so our own files from earlier seqs can never be awaited
    # again — reap them to bound the shared directory's growth
    for old in range(max(0, seq - 2), seq):
        try:
            os.unlink(g._file(name, old, g.process_id))
        except OSError:
            pass


def checkpoint_barrier(tag: str) -> None:
    """Cross-host agreement point of the sharded checkpoint protocol: all
    shard files must be visible on the shared FS before host 0 commits, and
    no host may proceed past the commit before it exists. Guarded (timeout
    -> failure agreement) when a barrier guard is configured; otherwise a
    bare `sync_global_devices` — a save must still be coordinated even when
    the operator disabled the timeout protocol. Single process: no-op."""
    if jax.process_count() <= 1:
        return
    if _BARRIER is not None:
        guarded_barrier(f"ckpt.{tag}")
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(f"mgproto_ckpt_{tag}")
