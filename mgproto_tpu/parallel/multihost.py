"""Multi-host (multi-process) helpers.

Under `jax.distributed` each process addresses only its own chips, so two
host-side idioms that are trivial on one host need care:

  * reading back a data-sharded array (`jax.device_get` of a global array
    whose shards live on other hosts raises "not fully addressable") —
    `host_local_rows` extracts exactly the rows this process contributed;
  * computing dataset-level metrics (accuracy, OoD percentiles, push
    candidates) over per-process shards — `allgather_rows` concatenates
    equal-shaped host-local arrays across processes (the loaders guarantee
    equal shapes: every process runs the same number of identically padded
    batches, data/loader.py).

Everything degenerates to a no-op/device_get on a single process. The REAL
branches are exercised in CI by tests/test_multiprocess.py: two coordinated
`jax.distributed` CPU processes (4 virtual devices each) drive allgather,
put_batch, fetch_replicated, a sharded train step, and the loader's
shard_index>0 path end to end.

Reference: none — the reference is single-process (SURVEY.md §2.3); this is
the scaffolding its NCCL/torch.distributed story never grew.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def is_primary_host() -> bool:
    """True on the one process that owns run-wide side effects (telemetry
    sinks, checkpoints' metadata): process 0. Single process: True."""
    return jax.process_index() == 0


def host_local_rows(arr: jax.Array) -> np.ndarray:
    """Rows of a leading-axis-sharded global array that live on THIS process,
    in ascending global-row order. Single process: the whole array."""
    if jax.process_count() == 1:
        return np.asarray(jax.device_get(arr))
    by_start = {}
    for s in arr.addressable_shards:
        start = s.index[0].start or 0
        by_start.setdefault(start, np.asarray(s.data))  # dedupe replicas
    return np.concatenate(
        [by_start[k] for k in sorted(by_start)], axis=0
    )


def allgather_rows(x: np.ndarray) -> np.ndarray:
    """Concatenate equal-shaped per-process host arrays across all processes
    (row-major in process order). Single process: identity."""
    if jax.process_count() == 1:
        return x
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(np.asarray(x))
    return np.concatenate(list(stacked), axis=0)


def allgather_sum(x: float) -> float:
    """Sum a host-side scalar across processes. Single process: identity."""
    if jax.process_count() == 1:
        return float(x)
    from jax.experimental import multihost_utils

    return float(np.sum(multihost_utils.process_allgather(np.float64(x))))


def any_across_hosts(flag: bool) -> bool:
    """True when ANY process passes True — the preemption agreement: a
    SIGTERM lands on ONE host, but every host must stop after the SAME step
    or the next collective deadlocks. A collective itself (every process
    must call it at the same cadence); single process: identity."""
    if jax.process_count() == 1:
        return bool(flag)
    return allgather_sum(1.0 if flag else 0.0) > 0.0


_REPLICATING_JITS: dict = {}


def _replicating_identity(mesh):
    """Per-mesh cached identity jit with replicated out_shardings (jit's own
    cache then handles distinct tree structures) — a fresh lambda per call
    would retrace every push/interpret invocation."""
    fn = _REPLICATING_JITS.get(mesh)
    if fn is None:
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        fn = jax.jit(lambda t: t, out_shardings=rep)
        _REPLICATING_JITS[mesh] = fn
    return fn


def fetch_replicated(tree: Any, mesh=None) -> Any:
    """Host-local numpy copy of a (possibly cross-host-sharded) pytree.

    Sharded leaves are first replicated by an SPMD identity (XLA all-gathers
    over ICI/DCN), making every leaf fully addressable; then device_get.
    Used by host-driven passes (push scan, interpretability) that re-run
    their own local jits over per-process batches."""
    leaves = jax.tree_util.tree_leaves(tree)
    needs_gather = any(
        isinstance(l, jax.Array) and not l.is_fully_addressable for l in leaves
    )
    if needs_gather:
        if mesh is None:
            raise ValueError("fetch_replicated needs the mesh for sharded input")
        tree = _replicating_identity(mesh)(tree)
    return jax.device_get(tree)
