"""Distributed runtime: device mesh, shardings, SPMD trainer.

Replaces the reference's `torch.nn.DataParallel` single-process replication
(reference main.py:184) with a first-class mesh runtime over ICI/DCN
(SURVEY.md §2.3, §5.8)."""

from mgproto_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    initialize_distributed,
    make_mesh,
)
from mgproto_tpu.parallel.sharding import (
    batch_sharding,
    class_sharding,
    put_batch,
    replicated,
    state_shardings,
)
from mgproto_tpu.parallel.trainer import ShardedTrainer

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "initialize_distributed",
    "make_mesh",
    "batch_sharding",
    "class_sharding",
    "put_batch",
    "replicated",
    "state_shardings",
    "ShardedTrainer",
]
