"""Device mesh construction + multi-host bring-up.

The reference's entire parallelism story is single-process
`torch.nn.DataParallel` (reference main.py:184, run.sh:12 — one GPU). The
TPU-native equivalent (SURVEY.md §2.3, §5.8) is one global `jax.sharding.Mesh`
over every chip with two logical axes:

  * ``data``  — batch sharding (the DP axis); gradients and BatchNorm batch
    statistics reduce over it automatically under SPMD jit.
  * ``model`` — class-axis sharding of the GMM head, memory bank and EM (the
    tensor-parallel analogue for this model family: classes are independent
    until the final [B, C] stack, SURVEY.md §5.7).

Multi-host pods: call `initialize_distributed()` once per process before any
jax op; the mesh then spans all processes' devices and pjit collectives ride
ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs):
    """`jax.shard_map` across the jax versions this repo meets: the CPU CI
    image ships 0.4.x (shard_map lives in jax.experimental with a
    `check_rep` kwarg) while the TPU relay runs a current jax (top-level
    `jax.shard_map` with `check_vma`). Both checks are disabled for the
    same reason: the wrapped bodies contain pallas_call/custom_vjp
    primitives the replication/varying-axis checker cannot see through
    (core/mgproto._fused_pool's long-standing caveat)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


_distributed_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    strict: bool = False,
) -> None:
    """Multi-host bring-up (idempotent). Must run before any other jax call.
    On TPU pods all three arguments are auto-detected from the environment; on
    CPU/GPU clusters pass them explicitly. Replaces the reference's absent
    `torch.distributed` story.

    With explicit arguments or strict=True, failures propagate (a worker
    silently falling back to single-host would train a divergent model while
    the rest of the pod hangs at the coordinator barrier). With no arguments
    the call is best-effort: on single-host environments with nothing to
    auto-detect it is a no-op."""
    global _distributed_initialized
    if _distributed_initialized:
        return
    explicit = (
        strict or coordinator_address is not None or num_processes is not None
    )
    try:
        # pod bring-up is the classic transient-failure window (workers race
        # the coordinator coming up; DCN flaps during scheduling) — retry
        # with backoff through the shared resilience path before giving up
        from mgproto_tpu.resilience.retry import retry_call

        retry_call(
            jax.distributed.initialize,
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            retries=3,
            base_delay=1.0,
            max_delay=10.0,
            retry_on=(RuntimeError,),  # connection errors, not config errors
            scope="distributed_init",
        )
        _distributed_initialized = True
    except (ValueError, RuntimeError):
        if explicit:
            raise


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global 2-axis mesh.

    Args:
      data:  size of the data axis; -1 = all remaining devices.
      model: size of the model (class-sharding) axis.
      devices: defaults to `jax.devices()` (global, all processes).
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if model < 1 or n % model:
        raise ValueError(f"model axis {model} must divide device count {n}")
    if data == -1:
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    return Mesh(np.asarray(devs).reshape(data, model), (DATA_AXIS, MODEL_AXIS))
