"""Sharding specs for the MGProto train state and data batches.

Layout (SURVEY.md §2.3 "TPU-native equivalent"):

  * batch arrays         -> P('data')   — sharded on the leading batch axis.
  * net params/opt state -> replicated  — the whole model is ~20M params; DP
    replication is the right call (prototype tensors are tiny: 200x10x64).
  * gmm / memory / EM optimizer state -> P('model') on the CLASS axis when the
    mesh has a model axis — per-class density, enqueue and EM are all
    class-independent, so the (B*H*W) x (C*K) density matrix and the
    [C, cap, d] memory bank partition cleanly (SURVEY.md §5.7's
    ImageNet-1000 stretch layout).

Under SPMD jit the three replica hazards of the reference become collectives
XLA inserts for us: memory enqueue sees the global batch (all_gather over
'data'), gradients and BatchNorm batch stats psum over 'data', and the EM
sufficient statistics stay local to each class shard (no collective at all).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mgproto_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding over the data axis (any rank)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def class_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding over the model axis (any rank)."""
    return NamedSharding(mesh, P(MODEL_AXIS))


def _class_shard_tree(tree: Any, mesh: Mesh, num_classes: int) -> Any:
    """Shard every leaf whose leading axis is the class axis; replicate the
    rest (e.g. optax scalar step counters)."""
    repl = replicated(mesh)
    cls = class_sharding(mesh)
    model_size = mesh.shape[MODEL_AXIS]

    def per_leaf(x):
        if (
            hasattr(x, "ndim")
            and x.ndim >= 1
            and x.shape[0] == num_classes
            and num_classes % model_size == 0
        ):
            return cls
        return repl

    return jax.tree.map(per_leaf, tree)


def state_shardings(state: Any, mesh: Mesh, num_classes: int) -> Any:
    """A TrainState-shaped pytree of NamedShardings for `state`."""
    repl = replicated(mesh)
    sh = jax.tree.map(lambda _: repl, state)
    if mesh.shape[MODEL_AXIS] > 1:
        sh = sh.replace(
            gmm=_class_shard_tree(state.gmm, mesh, num_classes),
            memory=_class_shard_tree(state.memory, mesh, num_classes),
            proto_opt_state=_class_shard_tree(
                state.proto_opt_state, mesh, num_classes
            ),
        )
    return sh


def put_batch(batch: Any, mesh: Mesh) -> Any:
    """Place a host batch onto the mesh, sharded on the data axis.

    Single-process: a plain sharded device_put of the global batch.
    Multi-host: each process passes its LOCAL shard of the global batch and
    the global array is assembled across processes (the `jax.distributed`
    path the reference has no analogue for)."""
    sh = batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(batch, sh)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)),
        batch,
    )
