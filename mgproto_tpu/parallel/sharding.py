"""Sharding specs for the MGProto train state and data batches.

Layout (SURVEY.md §2.3 "TPU-native equivalent", grown to the weak-scaling
layout of ISSUE 14):

  * batch arrays -> P(('data', 'model')) — sharded on the leading batch axis
    over EVERY chip. The model-axis devices used to hold full batch replicas
    and redundantly recompute the whole trunk; spreading the rows over both
    axes makes the trunk weak-scale with the total chip count while the
    class-sharded head keeps its layout (GSPMD inserts the row/class
    reshards where the [B, C] density stack needs them).
  * net params + Adam moments -> per-param sharded over 'model'
    (SNIPPETS.md [2]'s per-param sharding-map pattern): each array leaf is
    split on its LARGEST axis divisible by the model-axis size, so master
    f32 params and both optimizer-moment trees scale ~1/model_axis per chip
    instead of replicating — at ImageNet-1000 scale the replicated Adam
    moments, not the model, are the first per-chip HBM funnel. Leaves with
    no divisible axis (odd shapes, scalars) stay replicated; model axis of
    1 reproduces the historical fully-replicated layout bit-for-bit.
  * gmm / memory / EM optimizer state -> P('model') on the CLASS axis —
    per-class density, enqueue and EM are all class-independent, so the
    (B*H*W) x (C*K) density matrix and the [C, cap, d] memory bank
    partition cleanly (SURVEY.md §5.7's ImageNet-1000 layout). The EM over
    these shards runs shard-local with psum'd statistics (core/em.py
    `_sharded_em_update`) — no shard ever materializes another's bank.

Every TrainState field MUST have an entry in `SHARDING_RULES`: a new state
field that nobody thought about would otherwise silently replicate — at
bank scale that is the per-chip HBM funnel this module exists to prevent —
so `state_partition_specs` raises on unknown fields and
`scripts/check_sharding_coverage.py` lints the contract in tier-1.

Under SPMD jit the three replica hazards of the reference become collectives
XLA inserts for us: memory enqueue sees the global batch (all_gather over
the batch axes), gradients and BatchNorm batch stats psum over them, and
the EM sufficient statistics stay local to each class shard.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mgproto_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# field -> rule for every TrainState field (core/state.py). Rules:
#   replicate — small or step-coupled state every chip needs whole
#   param     — per-param map: largest model_size-divisible axis -> 'model'
#   class     — leading class axis -> 'model' (bank/EM locality contract)
# `state_partition_specs` REFUSES fields absent from this table (see the
# module docstring; scripts/check_sharding_coverage.py is the tier-1 gate).
SHARDING_RULES: Dict[str, str] = {
    "step": "replicate",
    "params": "param",
    "batch_stats": "replicate",  # BN running stats: tiny, read every step
    "gmm": "class",
    "memory": "class",
    "opt_state": "param",  # joint Adam moments shard with their params
    "warm_opt_state": "param",
    "proto_opt_state": "class",  # EM mean-Adam moments: class-leading
}


class ShardingCoverageError(ValueError):
    """A TrainState field has no entry in SHARDING_RULES — it would silently
    replicate (the bank-scale per-chip HBM funnel). Add an explicit rule."""


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_spec() -> P:
    """Leading-axis batch partitioning over BOTH mesh axes (docstring)."""
    return P((DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding of a batch array over every chip."""
    return NamedSharding(mesh, batch_spec())


def class_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding over the model axis (any rank)."""
    return NamedSharding(mesh, P(MODEL_AXIS))


def _class_spec_tree(tree: Any, num_classes: int, model_size: int) -> Any:
    """PartitionSpec per leaf: P('model') on the leading class axis when it
    shards evenly; P() for the rest (e.g. optax scalar step counters)."""

    def per_leaf(x):
        if (
            hasattr(x, "ndim")
            and x.ndim >= 1
            and x.shape[0] == num_classes
            and num_classes % model_size == 0
        ):
            return P(MODEL_AXIS)
        return P()

    return jax.tree.map(per_leaf, tree)


def param_partition_spec(shape, model_size: int) -> P:
    """The per-param rule (SNIPPETS.md [2] pattern, shapes instead of a
    name map — this state has no repeated layer stacks to wildcard): shard
    the LARGEST axis divisible by `model_size`; ties break toward the last
    axis (output channels for HWIO conv kernels, the conventionally-largest
    dim). No divisible axis (or model_size 1) -> replicated."""
    if model_size <= 1 or not shape:
        return P()
    best = None  # (size, axis)
    for axis, dim in enumerate(shape):
        if dim % model_size == 0 and dim >= model_size:
            if best is None or dim >= best[0]:
                best = (dim, axis)
    if best is None:
        return P()
    spec = [None] * len(shape)
    spec[best[1]] = MODEL_AXIS
    return P(*spec)


def _param_spec_tree(tree: Any, model_size: int) -> Any:
    return jax.tree.map(
        lambda x: param_partition_spec(getattr(x, "shape", ()), model_size),
        tree,
    )


def state_partition_specs(state: Any, num_classes: int, model_size: int) -> Any:
    """A TrainState-shaped pytree of PartitionSpecs for `state`, from the
    SHARDING_RULES table. Pure shape math (no mesh, no devices) so the
    HBM planner and the coverage lint can audit it off-device; raises
    `ShardingCoverageError` on a field the table does not name."""
    fields = (
        state._fields if hasattr(state, "_fields")
        else tuple(f.name for f in state.__dataclass_fields__.values())
    )
    missing = [f for f in fields if f not in SHARDING_RULES]
    if missing:
        raise ShardingCoverageError(
            f"TrainState field(s) {missing} have no SHARDING_RULES entry — "
            "an unruled field silently replicates on every chip (the "
            "bank-scale HBM funnel). Add an explicit rule in "
            "parallel/sharding.py and re-run "
            "scripts/check_sharding_coverage.py."
        )
    out = {}
    for f in fields:
        sub = getattr(state, f)
        rule = SHARDING_RULES[f]
        if model_size <= 1 or rule == "replicate":
            out[f] = jax.tree.map(lambda _: P(), sub)
        elif rule == "class":
            out[f] = _class_spec_tree(sub, num_classes, model_size)
        elif rule == "param":
            out[f] = _param_spec_tree(sub, model_size)
        else:  # pragma: no cover — the table is module-local
            raise ValueError(f"unknown sharding rule {rule!r} for {f!r}")
    if hasattr(state, "_fields"):
        return type(state)(**out)
    return state.replace(**out)


def spec_shard_factor(spec: P, model_size: int) -> int:
    """How many ways `spec` splits an array over the model axis (the
    divisor `bytes -> bytes-per-chip` accounting uses). The data axis is
    not counted: state leaves never shard over it."""
    factor = 1
    for entry in spec:
        names = entry if isinstance(entry, tuple) else (entry,)
        if MODEL_AXIS in names:
            factor *= model_size
    return factor


def tree_bytes_per_chip(tree: Any, spec_tree: Any, model_size: int) -> int:
    """Per-chip bytes of `tree` under `spec_tree` (shape math only; works
    on ShapeDtypeStructs). The weak-scaling per-chip measure: replicated
    leaves charge full size, sharded leaves 1/factor."""
    total = 0
    for leaf, spec in zip(
        jax.tree_util.tree_leaves(tree),
        jax.tree_util.tree_leaves(
            spec_tree, is_leaf=lambda x: isinstance(x, P)
        ),
    ):
        if not hasattr(leaf, "shape"):
            continue
        nbytes = int(math.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        total += nbytes // spec_shard_factor(spec, model_size)
    return int(total)


def state_shardings(state: Any, mesh: Mesh, num_classes: int) -> Any:
    """A TrainState-shaped pytree of NamedShardings for `state` — the spec
    tree from `state_partition_specs` bound to `mesh`."""
    specs = state_partition_specs(
        state, num_classes, mesh.shape[MODEL_AXIS]
    )
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def put_batch(batch: Any, mesh: Mesh) -> Any:
    """Place a host batch onto the mesh, sharded on the leading batch axis
    over every chip.

    Single-process: a plain sharded device_put of the global batch.
    Multi-host: each process passes its LOCAL shard of the global batch and
    the global array is assembled across processes (the `jax.distributed`
    path the reference has no analogue for)."""
    sh = batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(batch, sh)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)),
        batch,
    )
