"""Mesh-sharded Trainer: the distributed runtime around engine.train.Trainer.

The step functions themselves are unchanged — SPMD jit partitions the same
program the single-chip Trainer runs, with shardings pinned so that:

  * the batch lives split over 'data' (scatter the reference does per forward
    via DataParallel, main.py:184 — here it never materializes unsharded);
  * params/opt state are replicated and gradients arrive all-reduced (the
    NCCL allreduce the reference never got to, SURVEY.md §2.3);
  * gmm/memory/EM state is class-sharded over 'model' when the mesh has one,
    so density scoring, enqueue and EM scale past 1000 classes.

This design FIXES the reference's lost-update bug by construction: memory
enqueue candidates from every data shard are globally visible to the one
logical `memory_push` (reference loses all non-primary replicas' writes,
model.py:228-252 under DataParallel).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from mgproto_tpu.config import Config
from mgproto_tpu.engine.train import (
    BankStepOut,
    EvalOutput,
    Trainer,
    TrainMetrics,
    TrunkOut,
)
from mgproto_tpu.core.state import TrainState, split_state
from mgproto_tpu.parallel.mesh import make_mesh
from mgproto_tpu.parallel.sharding import (
    batch_sharding,
    put_batch,
    replicated,
    state_shardings,
)


class ShardedTrainer(Trainer):
    """Trainer whose jitted steps run SPMD over a device mesh.

    Usage:
        trainer = ShardedTrainer(cfg, steps_per_epoch)       # mesh from cfg
        state = trainer.init_state(rng)                       # sharded state
        state, m = trainer.train_step(state, images, labels, ...)

    State restored from a checkpoint must pass through `prepare(state)` once
    before stepping.
    """

    def __init__(
        self,
        cfg: Config,
        steps_per_epoch: int,
        mesh: Optional[Mesh] = None,
        donate: bool = False,
    ):
        super().__init__(cfg, steps_per_epoch, donate=donate)
        self.mesh = mesh if mesh is not None else make_mesh(
            cfg.mesh.data, cfg.mesh.model
        )
        n_data = self.mesh.shape["data"]
        n_model = self.mesh.shape["model"]
        nproc = jax.process_count()
        if n_data % nproc != 0:
            raise ValueError(
                f"mesh data axis ({n_data}) must be divisible by the process "
                f"count ({nproc}) so every host owns whole data shards"
            )
        # batches shard over BOTH mesh axes (parallel/sharding.py
        # batch_spec): the model-axis devices carry batch rows too instead
        # of redundantly recomputing the whole trunk per class shard, so
        # the divisibility unit is this process's share of ALL chips
        local_chips = (n_data * n_model) // nproc
        for name, b in (
            ("train_batch_size", cfg.data.train_batch_size),
            ("test_batch_size", cfg.data.test_batch_size),
            ("train_push_batch_size", cfg.data.train_push_batch_size),
        ):
            # batch sizes are per-process (the loaders shard by process and
            # put_batch assembles the global batch of b * nproc rows)
            if b % local_chips != 0:
                raise ValueError(
                    f"data.{name}={b} (per process) must be divisible by this "
                    f"process's share of the mesh ({local_chips} of "
                    f"{n_data}x{n_model} devices); adjust --batch_size or "
                    "the mesh axes"
                )
        self._repl = replicated(self.mesh)
        self._batch_sh = batch_sharding(self.mesh)
        self._state_sh = None  # built lazily from the first state seen
        # placed zero-seed arrays by global batch size: with device_augment
        # off the loader ships no seeds, and a host-side zeros array must
        # NOT force an already-placed (prefetched) batch back through
        # put_batch — under multi-host that would np.asarray a
        # non-addressable global array; here the inert stream is placed
        # once and reused
        self._zero_seeds: dict = {}
        # With a sharded class axis, the fused Pallas kernel runs via
        # shard_map over this mesh (core/mgproto.py _fused_pool): each model
        # shard scores its local prototype slab, so the 1.9x kernel survives
        # exactly where the density matrix is largest (VERDICT r4 item 2 —
        # the old code silently downgraded to the unfused path here). Safe to
        # rebind after super().__init__: the jitted steps trace (and read
        # _score_mesh/_fused) on first call, not at jit-wrap time.
        if self.mesh.shape["model"] > 1:
            if cfg.model.num_classes % self.mesh.shape["model"] == 0:
                self._score_mesh = self.mesh
            elif cfg.model.fused_scoring is True:
                # explicitly forced fused but classes can't shard over the
                # model axis: fail HERE with an actionable message instead of
                # an opaque SPMD partitioner error at first step (ADVICE r4)
                raise ValueError(
                    f"fused_scoring=True requires num_classes "
                    f"({cfg.model.num_classes}) divisible by the mesh model "
                    f"axis ({self.mesh.shape['model']}); adjust --mesh_model "
                    "or drop --fused_scoring"
                )
            else:
                self._fused = False  # auto: XLA path for non-divisible C

    # -------------------------------------------------------------- plumbing
    def _build_jits(self, state_sh: Any) -> None:
        self._state_sh = state_sh
        # pjit forbids kwargs alongside in_shardings, so the static `warm`
        # flag becomes two compiled variants dispatched host-side (matching
        # the two optimizer topologies, reference main.py:205-220). The
        # batch triple (images, labels, seeds) all shard over 'data' — the
        # u8 wire batch and its augmentation seeds travel together.
        in_sh = (
            state_sh, self._batch_sh, self._batch_sh, self._batch_sh,
            self._repl, self._repl,
        )
        out_sh = (state_sh, self._repl)
        jits = {
            w: jax.jit(
                functools.partial(self._step, warm=w),
                in_shardings=in_sh,
                out_shardings=out_sh,
                donate_argnums=(0,) if self.donate else (),
            )
            for w in (False, True)
        }
        self._train_step = (
            lambda state, images, labels, seeds, mine, gmm, warm=False: (
                jits[bool(warm)](state, images, labels, seeds, mine, gmm)
            )
        )
        eval_out_sh = EvalOutput(
            logits=self._batch_sh, log_px=self._batch_sh, correct=self._batch_sh
        )
        self._eval_step = jax.jit(
            self._eval,
            in_shardings=(state_sh, self._batch_sh, self._batch_sh),
            out_shardings=eval_out_sh,
        )
        # async bank pipeline: the SAME trunk/bank split as the single-chip
        # Trainer, SPMD-sharded. The trunk reads the (one-step-stale) gmm at
        # its class sharding; the bank program keeps gmm/memory/EM state
        # class-sharded and its enqueue operands data-sharded — inside it,
        # GSPMD inserts the same all-gather (enqueue sees the global batch)
        # and the shard_mapped EM keeps its psum'd sufficient statistics,
        # so staleness changes WHEN the collectives run, never their
        # pattern: every shard follows the same one-step-stale schedule.
        trunk_sh, bank_sh = split_state(state_sh)
        trunk_out_sh = TrunkOut(
            enq_feats=self._batch_sh,
            enq_classes=self._batch_sh,
            enq_valid=self._batch_sh,
            step0=self._repl,
            finite=self._repl,
            loss=self._repl,
            cross_entropy=self._repl,
            mine=self._repl,
            aux=self._repl,
            accuracy=self._repl,
        )
        trunk_jits = {
            w: jax.jit(
                functools.partial(self._trunk_step, warm=w),
                in_shardings=(
                    trunk_sh, bank_sh.gmm, self._batch_sh, self._batch_sh,
                    self._batch_sh, self._repl,
                ),
                out_shardings=(trunk_sh, trunk_out_sh),
                donate_argnums=(0,) if self.donate else (),
            )
            for w in (False, True)
        }
        self._trunk_jit = (
            lambda trunk, gmm, images, labels, seeds, use_mine, warm=False: (
                trunk_jits[bool(warm)](
                    trunk, gmm, images, labels, seeds, use_mine
                )
            )
        )
        bank_out_sh = BankStepOut(
            num_active=self._repl,
            compact_fallback=self._repl,
            full_mem_ratio=self._repl,
        )
        self._bank_jit = jax.jit(
            self._bank_step,
            in_shardings=(
                bank_sh, self._batch_sh, self._batch_sh, self._batch_sh,
                self._repl, self._repl, self._repl,
            ),
            out_shardings=(bank_sh, bank_out_sh),
            donate_argnums=(0,) if self.donate else (),
        )
        # telemetry recompile detection must watch the REAL jit objects, not
        # the dispatching lambda above (which has no _cache_size)
        self._step_jits = jits  # warm -> jit (lower_train_step reads this)
        self._jit_handles = (
            list(jits.values()) + list(trunk_jits.values())
            + [self._bank_jit, self._eval_step]
        )

    def prepare(self, state: TrainState) -> TrainState:
        """Pin `state` to its mesh sharding (and build the sharded jits)."""
        sh = state_shardings(state, self.mesh, self.cfg.model.num_classes)
        if self._state_sh is None:
            self._build_jits(sh)
        return jax.device_put(state, sh)

    def lower_train_step(self, state, images, labels, seeds=None,
                         warm: bool = False):
        """Lower (NOT compile) the monolithic SPMD train step for one
        operand set — the weak-scaling harness's measurement hook
        (`bench.py --measure weakscale` reads the compiled module's
        cost/memory analysis and collective byte counts from it; the same
        program `scripts/launch_pod.sh` runs on real hardware). Operands
        may be jax.Arrays or ShapeDtypeStructs; `prepare` must have built
        the sharded jits first."""
        import jax.numpy as jnp

        if self._state_sh is None:
            raise RuntimeError("call prepare(state) before lower_train_step")
        if seeds is None:
            seeds = jax.ShapeDtypeStruct((images.shape[0],), jnp.uint32)
        return self._step_jits[bool(warm)].lower(
            state, images, labels, seeds,
            jnp.asarray(1.0, jnp.float32), jnp.asarray(True, bool),
        )

    def init_state(self, rng: jax.Array, for_restore: bool = False) -> TrainState:
        return self.prepare(super().init_state(rng, for_restore=for_restore))

    def put_batch(self, batch: Any) -> Any:
        """Host batch (images, labels[, seeds]) -> data-sharded device
        arrays (multi-host aware). Host-side dtype conversion happens here
        so device-prefetched batches (engine/train.py train_epoch) arrive
        fully placed; uint8 images keep the 4x-smaller wire format."""
        images = batch[0]
        if not isinstance(images, jax.Array):
            images = np.asarray(images)
            if images.dtype != np.uint8:
                images = images.astype(np.float32, copy=False)
        out = [images]
        for x, dt in zip(batch[1:], (np.int32, np.uint32)):
            out.append(x if isinstance(x, jax.Array) else np.asarray(x, dt))
        return put_batch(tuple(out), self.mesh)

    def _placed(self, x: Any) -> bool:
        """True iff `x` already carries THIS trainer's batch sharding (i.e.
        it came through put_batch). A merely-default-device jax.Array (e.g.
        jnp.asarray in engine/evaluate.py) must still be placed: under
        multi-host, skipping put_batch would hand a process-local array to a
        step jitted over the global mesh."""
        return isinstance(x, jax.Array) and x.sharding == self._batch_sh

    # ----------------------------------------------------------------- steps
    def _zero_seed_stream(self, n_global: int) -> jax.Array:
        """A placed, batch-sharded zeros seed array for a global batch of
        `n_global` rows (cached per size — one placement, not one per
        step). Only consumed when device_augment is on, which implies
        loader-shipped seeds; this is the inert stream for direct callers."""
        s = self._zero_seeds.get(n_global)
        if s is None:
            local = n_global // max(jax.process_count(), 1)
            (s,) = put_batch(
                (np.zeros((local,), np.uint32),), self.mesh
            )
            self._zero_seeds[n_global] = s
        return s

    def train_step(
        self,
        state: TrainState,
        images: jax.Array,
        labels: jax.Array,
        use_mine: bool,
        update_gmm: bool,
        warm: bool = False,
        seeds=None,
    ) -> Tuple[TrainState, TrainMetrics]:
        if not (self._placed(images) and self._placed(labels)):
            # not batch-sharded yet: place now (prefetched batches skip this)
            if seeds is None:
                seeds = np.zeros((np.shape(images)[0],), np.uint32)
            images, labels, seeds = self.put_batch((images, labels, seeds))
        elif seeds is None:
            # prefetched seedless batch (device_augment off): a cached
            # placed zero stream — never un-place the prefetched operands
            seeds = self._zero_seed_stream(int(images.shape[0]))
        elif not self._placed(seeds):
            (seeds,) = put_batch(
                (np.asarray(seeds, np.uint32),), self.mesh
            )
        return Trainer.train_step(
            self, state, images, labels, use_mine, update_gmm, warm,
            seeds=seeds,
        )

    def eval_step(
        self, state: TrainState, images: jax.Array, labels=None
    ) -> EvalOutput:
        if labels is None:
            # sharded eval always carries a label array; -1 never matches argmax
            labels = np.full((np.shape(images)[0],), -1, np.int32)
        if not (self._placed(images) and self._placed(labels)):
            images, labels = self.put_batch((images, labels))
        return self._eval_step(state, images, labels)
