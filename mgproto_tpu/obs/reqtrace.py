"""End-to-end request tracing through the serving plane.

PR 7's serving plane answers "how is the fleet doing" (aggregate metrics);
this module answers "where did THIS request's 40 ms go". When enabled, each
request is tracked from the moment the frontend (or the replica supervisor,
for the batch faces) mints it, through admission, the micro-batcher's
coalescing wait, the replica it landed on, and the device dispatch — and on
the response leaving the system (the ONE `serving.response.record()` exit
point) the stages are emitted as explicit-timestamp spans into the
telemetry tracer's Chrome-trace export:

    frontend  arrival -> response        (whole request, outcome attr)
    batcher   enqueued -> dispatch start (queue wait + linger, trigger attr)
    replica   dispatch start -> response (replica-name lane)
    engine    dispatch start + device_s  (bucket + fill/pad attrs)

All timestamps come from the PLANE's injectable clock (`enable(clock=...)`)
— `time.monotonic` in production, the virtual clock in the load harness —
so the exported timeline is exact under seeded storms, not an artifact of
host scheduling. Per-stage latencies also land in the
`serving_stage_seconds{stage=queue|device|total}` histogram (rendered by
`mgproto-telemetry summarize`), and with `include_timings=True` the
breakdown is attached to the ServeResponse itself (`timings`), the opt-in
per-request answer to "why was I slow".

DISABLED IS FREE: every hook starts with a module-global `None` check and
mints nothing — zero per-request allocation on the steady-state path.
Jax-free, like the rest of the plane.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.telemetry.tracing import Tracer, default_tracer

# tid lanes in the exported Chrome trace: frontend spans on lane 0,
# replica/engine spans on a stable per-replica lane starting here
_REPLICA_TID_BASE = 1

# a request minted but never answered (client vanished pre-admission) must
# not leak its record forever; past this many pending records the oldest
# are dropped on the floor (counted) rather than growing unbounded
_MAX_PENDING = 100_000


@dataclasses.dataclass
class _ReqRecord:
    """Everything known about one in-flight request (clock-domain times)."""

    arrival: float
    enqueued: float = -1.0
    dispatch: float = -1.0
    device_s: float = 0.0
    replica: str = ""
    trigger: str = ""
    bucket: int = 0
    fill: float = 0.0


class ReqTraceState:
    def __init__(
        self,
        clock=None,
        tracer: Optional[Tracer] = None,
        include_timings: bool = False,
    ):
        self.clock = clock if clock is not None else time.monotonic
        # resolved once at enable: the load harness passes its own Tracer,
        # the serve CLI lets the live TelemetrySession's tracer collect it
        self.tracer = tracer if tracer is not None else default_tracer()
        self.include_timings = bool(include_timings)
        self.pending: Dict[str, _ReqRecord] = {}
        self.dropped = 0
        self._replica_tids: Dict[str, int] = {}
        # per-dispatch context (set by the batcher, consumed by the engine):
        # which replica's batcher triggered, why, and when the dispatch
        # window opened on the plane clock
        self.ctx_replica = ""
        self.ctx_trigger = ""
        self.ctx_t0: Optional[float] = None

    def replica_tid(self, name: str) -> int:
        tid = self._replica_tids.get(name)
        if tid is None:
            tid = self._replica_tids[name] = (
                _REPLICA_TID_BASE + len(self._replica_tids)
            )
        return tid


_STATE: Optional[ReqTraceState] = None


def enable(
    clock=None,
    tracer: Optional[Tracer] = None,
    include_timings: bool = False,
) -> ReqTraceState:
    """Turn request tracing on for this process; returns the state (tests
    inspect it). `clock` MUST be the same clock the plane's engines run on."""
    global _STATE
    _STATE = ReqTraceState(
        clock=clock, tracer=tracer, include_timings=include_timings
    )
    return _STATE


def disable() -> None:
    global _STATE
    _STATE = None


def enabled() -> bool:
    return _STATE is not None


# ------------------------------------------------------------------- hooks
def mint(request_id: str, now: Optional[float] = None) -> None:
    """Start a request's trace (frontend HTTP parse, or ReplicaSet.submit
    for frontend-less faces). Idempotent: the first mint wins, so the
    frontend's earlier arrival stamp is never overwritten downstream."""
    st = _STATE
    if st is None or request_id in st.pending:
        return
    if len(st.pending) >= _MAX_PENDING:
        # evict the OLDEST record (dict = insertion order): stale leaks
        # age out and tracing stays live for new traffic forever
        st.pending.pop(next(iter(st.pending)), None)
        st.dropped += 1
    st.pending[request_id] = _ReqRecord(
        arrival=st.clock() if now is None else float(now)
    )


def on_enqueue(request_id: str, enqueued_at: float) -> None:
    """Admission: the request entered a replica's queue (engine.submit)."""
    st = _STATE
    if st is None:
        return
    rec = st.pending.get(request_id)
    if rec is None:
        mint(request_id, now=enqueued_at)
        rec = st.pending.get(request_id)
        if rec is None:
            return
    rec.enqueued = float(enqueued_at)


def dispatch_context(replica: str, trigger: str, t0: float) -> None:
    """Set by the micro-batcher right before `engine.process_pending`: the
    replica lane, the dispatch trigger, and the dispatch-window open time."""
    st = _STATE
    if st is None:
        return
    st.ctx_replica = replica
    st.ctx_trigger = trigger
    st.ctx_t0 = float(t0)


def clear_dispatch_context() -> None:
    """Drop the batcher-set context. The batcher calls this after every
    pump (try/finally around `process_pending`): a dispatch that never
    reached `on_dispatch` — breaker open, empty pop, device error — must
    not leak its t0/replica/trigger into a later context-less dispatch."""
    st = _STATE
    if st is None:
        return
    st.ctx_replica = ""
    st.ctx_trigger = ""
    st.ctx_t0 = None


def on_dispatch(
    request_ids: List[str],
    bucket: int,
    fill: float,
    fallback_t0: Optional[float] = None,
) -> None:
    """The engine dispatched a batch: stamp every member with the dispatch
    window (batcher context when pumped, the engine's own clock otherwise),
    the device time, and the batch's pad state."""
    st = _STATE
    if st is None:
        return
    t0 = st.ctx_t0 if st.ctx_t0 is not None else fallback_t0
    now = st.clock()
    if t0 is None:
        t0 = now
    device_s = max(now - t0, 0.0)
    for rid in request_ids:
        rec = st.pending.get(rid)
        if rec is None:
            continue
        rec.dispatch = float(t0)
        rec.device_s = device_s
        rec.replica = st.ctx_replica
        rec.trigger = st.ctx_trigger
        rec.bucket = int(bucket)
        rec.fill = float(fill)
    # the dispatch itself is a timeline event (coalescing is visible as
    # many requests sharing one dispatch span)
    st.tracer.add_span(
        "dispatch",
        ts=t0,
        dur=device_s,
        tid=st.replica_tid(st.ctx_replica or "engine"),
        replica=st.ctx_replica or None,
        trigger=st.ctx_trigger or None,
        bucket=bucket,
        fill=fill,
        requests=len(request_ids),
    )
    st.ctx_replica = ""
    st.ctx_trigger = ""
    st.ctx_t0 = None


def plane_event(name: str, **attrs) -> None:
    """Instant marker on the plane timeline (replica kill/wedge detection,
    restarts, swap stages/flips) — load-test traces show these as zero-width
    ticks between the request spans."""
    st = _STATE
    if st is None:
        return
    st.tracer.add_span(name, ts=st.clock(), dur=0.0, tid=0, **attrs)


def finish(resp) -> Optional[Dict[str, Any]]:
    """Called by `serving.response.record()` — the one exit point — for
    every response leaving the system. Emits the stage spans + histograms,
    forgets the request, and returns the timing breakdown when the opt-in
    is on (None otherwise, including for untracked requests)."""
    st = _STATE
    if st is None:
        return None
    rec = st.pending.pop(resp.request_id, None)
    if rec is None:
        return None
    now = st.clock()
    total = max(now - rec.arrival, 0.0)
    rid = resp.request_id
    tracer = st.tracer
    tracer.add_span(
        "frontend", ts=rec.arrival, dur=total, tid=0,
        request=rid, outcome=resp.outcome,
    )
    timings: Dict[str, Any] = {"total_s": total}
    hist = _m.histogram(_m.STAGE_SECONDS)
    if rec.enqueued >= 0.0:
        queue_end = rec.dispatch if rec.dispatch >= 0.0 else now
        queue_s = max(queue_end - rec.enqueued, 0.0)
        tracer.add_span(
            "batcher", ts=rec.enqueued, dur=queue_s, tid=0,
            request=rid, trigger=rec.trigger or None,
        )
        timings["queue_s"] = queue_s
        hist.observe(queue_s, stage="queue")
    if rec.dispatch >= 0.0:
        tid = st.replica_tid(rec.replica or "engine")
        tracer.add_span(
            "replica", ts=rec.dispatch, dur=max(now - rec.dispatch, 0.0),
            tid=tid, request=rid, replica=rec.replica or None,
        )
        tracer.add_span(
            "engine", ts=rec.dispatch, dur=rec.device_s, tid=tid,
            request=rid, bucket=rec.bucket, fill=rec.fill,
        )
        timings["device_s"] = rec.device_s
        timings["pad_fraction"] = max(1.0 - rec.fill, 0.0)
        if rec.replica:
            timings["replica"] = rec.replica
        hist.observe(rec.device_s, stage="device")
    hist.observe(total, stage="total")
    return timings if st.include_timings else None
