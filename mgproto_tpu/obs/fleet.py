"""Straggler detection: turn barrier-arrival skew into a targeted capture.

At pod scale a slow HOST is indistinguishable from a slow MODEL unless the
collective layer says who everyone waited for. The guarded barrier
(parallel/multihost.py) already records every peer's arrival time for free
— the seq files' arrival stamps — and hands them to the observer registered with
`set_skew_observer`. `SkewMonitor` is that observer:

  * per barrier it computes THIS host's arrival skew (my arrival minus the
    earliest peer's) and whether this host was the LAST arriver;
  * an EMA of the skew, normalized by the step-time EMA (the wired
    telemetry StepMonitor's, else its own from `observe_step`), feeds the
    `host_step_skew_fraction` gauge — the fleet table's headline number;
  * when this host is the PERSISTENT last-arriver (skew-fraction EMA above
    `threshold` for `patience` consecutive barriers), it fires ONCE: a
    `straggler_suspected` event on the flight recorder, the
    `straggler_suspected_total` counter, and — exactly like PR 8's anomaly
    triggers — `ProfilerWindow.arm("straggler")`, so the trace capture
    happens on the straggling host ONLY (off-TPU the window degrades to its
    cost-analysis capture, keeping the whole path tier-1 testable).

A non-last arriver resets the streak, and after a firing the monitor holds
off for `cooldown` barriers so a persistently-skewed run cannot spend its
epoch writing traces. Single-host runs never construct one (cli/train gates
on process_count > 1), and the barrier layer only collects arrival stamps
while an observer is registered — the zero-extra-work guard.
"""

from __future__ import annotations

from typing import Dict, Optional

from mgproto_tpu.obs.flightrec import record_event
from mgproto_tpu.telemetry.session import SKEW_GAUGE, STRAGGLER_COUNTER


class SkewMonitor:
    """Per-barrier arrival-skew EMA + persistent-last-arriver trigger.

    Args:
      process_id: this host's jax.process_index().
      window: obs.profiler.ProfilerWindow to arm on detection (None: detect
        and record, but capture nothing).
      monitor: telemetry StepMonitor whose `ema_seconds` normalizes the
        skew (None: the monitor keeps its own EMA from `observe_step`).
      threshold: skew-fraction EMA that counts as "straggling" (<= 0
        disables the trigger; the gauge still updates).
      patience: consecutive last-arriver barriers above threshold before
        firing.
      cooldown: barriers to ignore after a firing.
      ema_alpha: EMA weight for skew and the fallback step EMA.
      log: optional line logger.
    """

    def __init__(
        self,
        process_id: int,
        window=None,
        monitor=None,
        threshold: float = 0.25,
        patience: int = 5,
        cooldown: int = 200,
        ema_alpha: float = 0.3,
        log=None,
    ):
        self.process_id = int(process_id)
        self.window = window
        self.monitor = monitor
        self.threshold = float(threshold)
        self.patience = max(int(patience), 1)
        self.cooldown = max(int(cooldown), 0)
        self.ema_alpha = float(ema_alpha)
        self.log = log
        self.fired = 0  # straggler firings (this process)
        self._skew_ema: Optional[float] = None
        self._step_ema: Optional[float] = None  # fallback denominator
        self._streak = 0
        self._barriers = 0
        self._cooldown_until = -1

    # ------------------------------------------------------------------ state
    @property
    def skew_fraction(self) -> float:
        """Current skew EMA / step-time EMA (the gauge's value)."""
        step = self._step_seconds()
        if not step or self._skew_ema is None:
            return 0.0
        return self._skew_ema / step

    def _step_seconds(self) -> Optional[float]:
        if self.monitor is not None:
            ema = self.monitor.ema_seconds
            if ema:
                return float(ema)
        return self._step_ema

    def _ema(self, prev: Optional[float], value: float) -> float:
        a = self.ema_alpha
        return value if prev is None else a * value + (1 - a) * prev

    # ------------------------------------------------------------------ hooks
    def observe_step(self, seconds: float) -> None:
        """Fallback step-time EMA for callers without a StepMonitor
        (engine/train.py feeds this at step cadence either way — the wired
        monitor, when present, simply wins as the denominator)."""
        self._step_ema = self._ema(self._step_ema, float(seconds))

    def observe_barrier(
        self, name: str, arrivals: Dict[int, float], wait_s: float = 0.0
    ) -> None:
        """The `set_skew_observer` callback: one completed barrier's
        per-peer arrival wall times (seq-file stamps)."""
        self._barriers += 1
        mine = arrivals.get(self.process_id)
        if mine is None or len(arrivals) < 2:
            return
        first = min(arrivals.values())
        last_pid = max(arrivals, key=lambda p: arrivals[p])
        self._skew_ema = self._ema(self._skew_ema, mine - first)
        frac = self.skew_fraction
        self._set_gauge(frac)
        if self.threshold <= 0:
            return
        if last_pid == self.process_id and frac >= self.threshold:
            self._streak += 1
        else:
            self._streak = 0
            return
        if self._barriers < self._cooldown_until:
            return
        if self._streak >= self.patience:
            self._fire(name, frac)

    # --------------------------------------------------------------- internals
    def _set_gauge(self, frac: float) -> None:
        from mgproto_tpu.telemetry.registry import default_registry

        default_registry().gauge(SKEW_GAUGE).set(frac)

    def _fire(self, name: str, frac: float) -> None:
        from mgproto_tpu.telemetry.registry import default_registry

        self.fired += 1
        self._streak = 0
        self._cooldown_until = self._barriers + self.cooldown
        default_registry().counter(STRAGGLER_COUNTER).inc()
        record_event(
            "straggler_suspected",
            barrier=name,
            skew_fraction=round(frac, 4),
            skew_ema_s=round(self._skew_ema or 0.0, 6),
            patience=self.patience,
        )
        if self.log:
            self.log(
                f"fleet: this host is the persistent last-arriver "
                f"(skew fraction {frac:.2f} over {self.patience} barriers)"
                + ("; arming profiler capture" if self.window else "")
            )
        if self.window is not None:
            self.window.arm("straggler")
