"""Performance observatory (ISSUE 8): the instrumentation loop that turns
"the run was slow" into line items.

Sits on top of the PR-1 telemetry substrate (registry / tracer / monitors)
and closes the loop ROADMAP item 2 opens — capture evidence, attribute the
stall budget, and make the numbers enforceable:

  profiler  — `ProfilerWindow`: arms `jax.profiler` trace capture for a
              configured step range or automatically on anomaly triggers
              (step-time spike vs EMA, recompile, loader-wait fraction);
              degrades to a cost-analysis-only capture off-TPU so the whole
              arming path is tier-1 testable.
  stall     — stall-budget attribution: a captured device trace (or the
              hermetic XLA cost-analysis fallback) apportioned into
              MXU-busy / HBM-bound / host+infeed / bubble buckets, with
              measured-vs-attainable MFU in the PERF.md decomposition.
              Driven by `scripts/trace_report.py`.
  reqtrace  — end-to-end request tracing through the serving plane:
              frontend -> batcher -> replica -> engine stage spans on the
              plane's injectable clock, per-stage latency histograms, and
              an opt-in timing breakdown on the ServeResponse. Zero
              per-request work when disabled.
  flightrec — bounded ring buffer of recent structured events (steps,
              dispatch triggers, breaker transitions, swaps, chaos
              injections, rollbacks) dumped to JSONL on divergence
              rollback, preemption, replica death or crash. Every event
              and dump carries this process's host index (multi-host dumps
              are mergeable, `.h<pid>`-suffixed off host 0).
  fleet     — `SkewMonitor`: per-barrier arrival-skew EMA from the guarded
              barrier's seq-file arrival stamps; a persistent last-arriver host
              fires the PR-8 anomaly trigger (targeted ProfilerWindow
              capture on the straggling host only) and lands a
              `straggler_suspected` event on the flight recorder.

Everything here is host-side; `stall`'s cost-analysis path is the only
module that touches jax, and only when asked to lower a program. The
regression gate lives in `cli/telemetry.py` (`mgproto-telemetry check`).
"""

from mgproto_tpu.obs.fleet import SkewMonitor
from mgproto_tpu.obs.flightrec import (
    FlightRecorder,
    get_recorder,
    record_event,
    set_recorder,
)

__all__ = [
    "FlightRecorder",
    "SkewMonitor",
    "get_recorder",
    "record_event",
    "set_recorder",
]
