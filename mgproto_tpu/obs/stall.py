"""Stall-budget attribution: where does the step time actually go?

PERF.md's headroom decomposition ends with "apportioning those needs the
profiler trace" — the measured 55.8% MFU vs the ~88.6% structural ceiling,
with the gap blamed on HBM stalls, host/infeed time and bubbles but never
itemized. This module produces that itemization as ONE schema, from either
evidence source:

  * a CAPTURED DEVICE TRACE (Chrome trace-event JSON, as written by
    `jax.profiler` / xprof or by our own exporters): device-op durations
    are classified by name into MXU / HBM / host+infeed buckets and the
    gaps on the busiest device lane become the bubble bucket.
  * the HERMETIC COST-ANALYSIS FALLBACK (CPU, tier-1): the production step
    program is lowered through the SAME `perf.planner.lower_split_programs`
    helper the auto-tuner and `bench.py --measure overlap` use, XLA's
    cost analysis supplies FLOPs + bytes accessed, and a roofline model
    apportions a (measured or modeled) step time.

Both paths emit the same report: step time split into five buckets that sum
to ~100% —

    mxu_busy        time the matrix units are doing the program's FLOPs
    hbm_bound       bandwidth time NOT hidden behind compute (bytes/BW minus
                    the compute it could overlap; the roofline's memory wall)
    collective_wait cross-host/chip collective time (all-reduce/all-gather
                    ops on the trace, or an externally measured host-side
                    barrier/collective wait — ISSUE 10's fleet dimension;
                    the cost fallback reports ZERO on one host)
    host_infeed     host + input-pipeline time the device sat waiting
    bubble          everything else (scheduling gaps, launch latency, the
                    residual between model and measurement)

— plus measured vs attainable MFU in the PERF.md decomposition (the
attainable bound defaults to the committed
`evidence/mfu_headroom_b256.json` flop-weighted tiling bound).

Driven by `scripts/trace_report.py`; `ProfilerWindow`'s off-TPU fallback
uses `step_costs` as its cost provider.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

# v5e reference peaks (overridable everywhere): bf16 MXU peak and HBM BW
DEFAULT_PEAK_FLOPS = 197e12
DEFAULT_HBM_BYTES_PER_S = 819e9
DEFAULT_ATTAINABLE_MFU = 0.886  # PERF.md structural ceiling (see below)

BUCKETS = (
    "mxu_busy", "hbm_bound", "collective_wait", "host_infeed", "bubble"
)

# ---------------------------------------------------------------- trace side
# device-op name -> bucket. Checked in order; first hit wins. Collectives
# come before HBM ("all-gather" contains the HBM token "gather") and before
# MXU; the MXU list is ahead of the HBM list: a fusion named
# "fusion.conv..." is matrix work even though plain "fusion" defaults to
# bandwidth-bound.
_HOST_TOKENS = (
    "infeed", "outfeed", "host", "transfer", "copy-start", "copy-done",
    "send", "recv",
)
_COLLECTIVE_TOKENS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective", "psum", "ppermute",
)
_MXU_TOKENS = (
    "convolution", "conv", "dot", "matmul", "gemm", "mxu", "einsum",
    "cublas", "custom-call",  # the fused Pallas scoring/E-step kernels
)
_HBM_TOKENS = (
    "copy", "scatter", "gather", "reduce", "broadcast", "transpose",
    "select", "concatenate", "slice", "pad", "iota", "sort", "fusion",
    "bitcast", "compare",
    "loop", "while", "dynamic-update",
)


def classify_op(name: str) -> str:
    """Bucket for one device-op (trace event) name."""
    n = name.lower()
    for tok in _HOST_TOKENS:
        if tok in n:
            return "host_infeed"
    for tok in _COLLECTIVE_TOKENS:
        if tok in n:
            return "collective_wait"
    for tok in _MXU_TOKENS:
        if tok in n:
            return "mxu_busy"
    for tok in _HBM_TOKENS:
        if tok in n:
            return "hbm_bound"
    return "hbm_bound"  # unknown elementwise tails are bandwidth-bound


def load_chrome_trace(path: str) -> List[Dict[str, Any]]:
    """traceEvents from a Chrome trace file (.json / .json.gz) or from the
    newest *.trace.json(.gz) under a profiler output directory."""
    if os.path.isdir(path):
        candidates = sorted(
            glob.glob(os.path.join(path, "**", "*.trace.json*"),
                      recursive=True),
            key=os.path.getmtime,
        )
        if not candidates:
            raise FileNotFoundError(
                f"no *.trace.json(.gz) under {path} — is this a profiler "
                "output directory?"
            )
        path = candidates[-1]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data) if isinstance(data, dict) else data
    if not isinstance(events, list):
        raise ValueError(f"{path} is not a Chrome trace")
    return events


def attribute_trace(
    events: Iterable[Dict[str, Any]],
    host_infeed_s: float = 0.0,
) -> Dict[str, Any]:
    """Bucket seconds from complete ('X') trace events. The busiest
    pid/tid lane is taken as THE device lane: its busy time is classified
    by op name, and the unoccupied remainder of its span is the bubble.
    `host_infeed_s` adds externally measured host wait (e.g. telemetry's
    loader_wait_fraction x step time) on top of host-named ops."""
    lanes: Dict[Tuple[Any, Any], Dict[str, float]] = {}
    per_lane_events: Dict[Tuple[Any, Any], List] = {}
    for e in events:
        if e.get("ph", "X") != "X":
            continue
        dur = float(e.get("dur", 0.0)) / 1e6
        if dur <= 0:
            continue
        key = (e.get("pid"), e.get("tid"))
        lane = lanes.setdefault(key, {"busy": 0.0})
        lane["busy"] += dur
        per_lane_events.setdefault(key, []).append(e)
    if not lanes:
        raise ValueError("trace has no complete events to attribute")
    device_lane = max(lanes, key=lambda k: lanes[k]["busy"])
    evs = per_lane_events[device_lane]
    buckets = {b: 0.0 for b in BUCKETS}
    t_min, t_max = float("inf"), float("-inf")
    for e in evs:
        ts = float(e.get("ts", 0.0)) / 1e6
        dur = float(e.get("dur", 0.0)) / 1e6
        buckets[classify_op(str(e.get("name", "?")))] += dur
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
    span = max(t_max - t_min, 0.0)
    busy = sum(buckets.values())
    buckets["bubble"] = max(span - busy, 0.0)
    buckets["host_infeed"] += max(float(host_infeed_s), 0.0)
    total = sum(buckets.values())
    return {
        "source": "trace",
        "device_lane": {"pid": device_lane[0], "tid": device_lane[1],
                        "events": len(evs)},
        "span_s": span,
        "step_time_s": total,
        "buckets": _fractions(buckets, total),
    }


# ------------------------------------------------------------ cost-model side
def step_costs(cfg, batch: Optional[int] = None) -> Dict[str, Any]:
    """FLOPs / bytes-accessed / peak-bytes of the production step program(s)
    for `cfg` at `batch` (per-chip), from XLA's compiled-module analyses —
    hermetic on CPU. Async-bank configs report trunk + bank separately and
    summed; sync configs the monolithic step. Shapes only: the state is
    `eval_shape`d, nothing real is allocated. Also the `cost_provider`
    behind ProfilerWindow's off-TPU fallback capture."""
    import jax
    import jax.numpy as jnp

    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.perf.planner import _program_peak, lower_split_programs

    trainer = Trainer(cfg, steps_per_epoch=100, donate=True)
    state = jax.eval_shape(
        lambda rng: trainer.init_state(rng, for_restore=True),
        jax.random.PRNGKey(0),
    )
    m = cfg.model
    b = int(batch) if batch else int(cfg.data.train_batch_size)
    img_dtype = jnp.uint8 if trainer._device_augment else jnp.float32
    images = jax.ShapeDtypeStruct((b, m.img_size, m.img_size, 3), img_dtype)
    labels = jax.ShapeDtypeStruct((b,), jnp.int32)
    seeds = jax.ShapeDtypeStruct((b,), jnp.uint32)
    use_mine = jnp.asarray(1.0, jnp.float32)
    update_gmm = jnp.asarray(True, bool)

    def _costs(compiled) -> Dict[str, Any]:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        peak, _ = _program_peak(compiled)
        return {
            "flops": float(ca.get("flops") or 0.0),
            "bytes_accessed": float(
                ca.get("bytes accessed", ca.get("bytes_accessed")) or 0.0
            ),
            "peak_bytes": int(peak),
        }

    programs: Dict[str, Dict[str, Any]] = {}
    if trainer.async_bank:
        trunk_l, bank_l = lower_split_programs(
            trainer, state, images, labels, seeds, use_mine, update_gmm
        )
        programs["trunk"] = _costs(trunk_l.compile())
        programs["bank"] = _costs(bank_l.compile())
    else:
        programs["step"] = _costs(
            trainer._train_step.lower(
                state, images, labels, seeds, use_mine, update_gmm,
                warm=False,
            ).compile()
        )
    return {
        "batch": b,
        "backend": jax.default_backend(),
        "async_bank": trainer.async_bank,
        "programs": programs,
        "flops": sum(p["flops"] for p in programs.values()),
        "bytes_accessed": sum(
            p["bytes_accessed"] for p in programs.values()
        ),
        "peak_bytes": sum(p["peak_bytes"] for p in programs.values()),
    }


def roofline_buckets(
    flops: float,
    bytes_accessed: float,
    step_time_s: Optional[float] = None,
    host_infeed_s: float = 0.0,
    collective_wait_s: float = 0.0,
    peak_flops: float = DEFAULT_PEAK_FLOPS,
    hbm_bytes_per_s: float = DEFAULT_HBM_BYTES_PER_S,
) -> Dict[str, Any]:
    """Apportion a step via the roofline: compute time is flops/peak, the
    HBM bucket is the bandwidth time compute cannot hide, host and
    collective time are whatever the caller measured (`collective_wait_s`
    is e.g. telemetry's per-step barrier+collective wait; the single-host
    cost fallback passes nothing and the line item reports ZERO, keeping
    the schema identical across fleet sizes).

    A MEASURED `step_time_s` is GROUND TRUTH: the buckets partition it
    exactly. The bandwidth model is an upper bound on stall time (XLA's
    bytes-accessed is fusion-pessimistic, especially on the CPU backend),
    so the HBM bucket is clamped into the measured residual after compute,
    host and collective time; whatever the bandwidth model cannot claim is
    the bubble. `hbm_model_clamped` flags when the clamp bit (the model had
    MORE traffic than the residual — read the HBM bucket as "at least
    this bound-ness", not a precise stall count). Without a measurement
    the modeled sum stands in (bubble 0) and the report says so.
    Fractions always sum to 1 of the reported step time."""
    mxu_s = flops / peak_flops if peak_flops > 0 else 0.0
    hbm_total_s = bytes_accessed / hbm_bytes_per_s if hbm_bytes_per_s else 0.0
    hbm_raw_s = max(hbm_total_s - mxu_s, 0.0)
    host_s = max(float(host_infeed_s), 0.0)
    coll_s = max(float(collective_wait_s), 0.0)
    measured = step_time_s is not None
    floor = mxu_s + host_s + coll_s
    if measured:
        # a step cannot be shorter than its compute + host + collective
        # floor; a measurement below it means the peaks are mis-set, and
        # the floor wins so the partition stays consistent
        total = max(float(step_time_s), floor)
        hbm_s = min(hbm_raw_s, max(total - floor, 0.0))
    else:
        total = floor + hbm_raw_s
        hbm_s = hbm_raw_s
    buckets = {
        "mxu_busy": mxu_s,
        "hbm_bound": hbm_s,
        "collective_wait": coll_s,
        "host_infeed": host_s,
        "bubble": max(total - mxu_s - hbm_s - host_s - coll_s, 0.0),
    }
    return {
        "source": "cost_analysis",
        "step_time_s": total,
        "step_time_measured": measured,
        "modeled_step_time_s": floor + hbm_raw_s,
        "hbm_total_s": hbm_total_s,
        "hbm_model_clamped": measured and hbm_raw_s > hbm_s,
        "buckets": _fractions(buckets, total),
    }


# ------------------------------------------------------------------- report
def _fractions(buckets: Dict[str, float], total: float) -> Dict[str, Any]:
    return {
        name: {
            "seconds": buckets[name],
            "fraction": buckets[name] / total if total > 0 else 0.0,
        }
        for name in BUCKETS
    }


def attainable_mfu_default(repo_root: Optional[str] = None) -> float:
    """The committed structural ceiling (mfu_headroom's FLOP-weighted MXU
    tiling bound), falling back to the PERF.md constant."""
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    path = os.path.join(root, "evidence", "mfu_headroom_b256.json")
    try:
        with open(path) as f:
            v = json.load(f).get("flop_weighted_mxu_eff_bound")
        if v:
            return float(v)
    except (OSError, ValueError):
        pass
    return DEFAULT_ATTAINABLE_MFU


def finish_report(
    attribution: Dict[str, Any],
    flops: Optional[float] = None,
    peak_flops: float = DEFAULT_PEAK_FLOPS,
    attainable_mfu: Optional[float] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Wrap a bucket attribution into the one stall-report schema: add the
    fraction-sum self-check and the measured-vs-attainable MFU line items
    (PERF.md decomposition: measured = flops / (step x peak), attainable =
    the array-padding ceiling, ratio = the stall tax the buckets itemize)."""
    report: Dict[str, Any] = {"stall_report": True, **attribution}
    fractions = [
        b["fraction"] for b in attribution["buckets"].values()
    ]
    report["fraction_sum"] = sum(fractions)
    att = (
        float(attainable_mfu) if attainable_mfu is not None
        else attainable_mfu_default()
    )
    report["attainable_mfu"] = att
    step = attribution.get("step_time_s") or 0.0
    if flops and step > 0 and peak_flops > 0:
        measured = flops / (step * peak_flops)
        report["flops"] = flops
        report["peak_flops"] = peak_flops
        report["measured_mfu"] = measured
        report["mfu_ratio_measured_over_attainable"] = (
            measured / att if att > 0 else None
        )
    if extra:
        report.update(extra)
    return report
