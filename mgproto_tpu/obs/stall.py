"""Stall-budget attribution: where does the step time actually go?

PERF.md's headroom decomposition ends with "apportioning those needs the
profiler trace" — the measured 55.8% MFU vs the ~88.6% structural ceiling,
with the gap blamed on HBM stalls, host/infeed time and bubbles but never
itemized. This module produces that itemization as ONE schema, from either
evidence source:

  * a CAPTURED DEVICE TRACE (Chrome trace-event JSON, as written by
    `jax.profiler` / xprof or by our own exporters): device-op durations
    are classified by name into MXU / HBM / host+infeed buckets and the
    gaps on the busiest device lane become the bubble bucket.
  * the HERMETIC COST-ANALYSIS FALLBACK (CPU, tier-1): the production step
    program is lowered through the SAME `perf.planner.lower_split_programs`
    helper the auto-tuner and `bench.py --measure overlap` use, XLA's
    cost analysis supplies FLOPs + bytes accessed, and a roofline model
    apportions a (measured or modeled) step time.

Both paths emit the same report: step time split into five buckets that sum
to ~100% —

    mxu_busy        time the matrix units are doing the program's FLOPs
    hbm_bound       bandwidth time NOT hidden behind compute (bytes/BW minus
                    the compute it could overlap; the roofline's memory wall)
    collective_wait cross-host/chip collective time (all-reduce/all-gather
                    ops on the trace, or an externally measured host-side
                    barrier/collective wait — ISSUE 10's fleet dimension;
                    the cost fallback reports ZERO on one host)
    host_infeed     host + input-pipeline time the device sat waiting
    bubble          everything else (scheduling gaps, launch latency, the
                    residual between model and measurement)

— plus measured vs attainable MFU in the PERF.md decomposition (the
attainable bound defaults to the committed
`evidence/mfu_headroom_b256.json` flop-weighted tiling bound).

Driven by `scripts/trace_report.py`; `ProfilerWindow`'s off-TPU fallback
uses `step_costs` as its cost provider.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

# v5e reference peaks (overridable everywhere): bf16 MXU peak and HBM BW
DEFAULT_PEAK_FLOPS = 197e12
DEFAULT_HBM_BYTES_PER_S = 819e9
DEFAULT_ATTAINABLE_MFU = 0.886  # PERF.md structural ceiling (see below)

BUCKETS = (
    "mxu_busy", "hbm_bound", "collective_wait", "host_infeed", "bubble"
)

# ---------------------------------------------------------------- trace side
# device-op name -> bucket. Checked in order; first hit wins. Collectives
# come before HBM ("all-gather" contains the HBM token "gather") and before
# MXU; the MXU list is ahead of the HBM list: a fusion named
# "fusion.conv..." is matrix work even though plain "fusion" defaults to
# bandwidth-bound.
_HOST_TOKENS = (
    "infeed", "outfeed", "host", "transfer", "copy-start", "copy-done",
    "send", "recv",
)
_COLLECTIVE_TOKENS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective", "psum", "ppermute",
)
_MXU_TOKENS = (
    "convolution", "conv", "dot", "matmul", "gemm", "mxu", "einsum",
    "cublas", "custom-call",  # the fused Pallas scoring/E-step kernels
)
_HBM_TOKENS = (
    "copy", "scatter", "gather", "reduce", "broadcast", "transpose",
    "select", "concatenate", "slice", "pad", "iota", "sort", "fusion",
    "bitcast", "compare",
    "loop", "while", "dynamic-update",
)


def classify_op(name: str) -> str:
    """Bucket for one device-op (trace event) name."""
    n = name.lower()
    for tok in _HOST_TOKENS:
        if tok in n:
            return "host_infeed"
    for tok in _COLLECTIVE_TOKENS:
        if tok in n:
            return "collective_wait"
    for tok in _MXU_TOKENS:
        if tok in n:
            return "mxu_busy"
    for tok in _HBM_TOKENS:
        if tok in n:
            return "hbm_bound"
    return "hbm_bound"  # unknown elementwise tails are bandwidth-bound


def load_chrome_trace(path: str) -> List[Dict[str, Any]]:
    """traceEvents from a Chrome trace file (.json / .json.gz) or from the
    newest *.trace.json(.gz) under a profiler output directory."""
    if os.path.isdir(path):
        candidates = sorted(
            glob.glob(os.path.join(path, "**", "*.trace.json*"),
                      recursive=True),
            key=os.path.getmtime,
        )
        if not candidates:
            raise FileNotFoundError(
                f"no *.trace.json(.gz) under {path} — is this a profiler "
                "output directory?"
            )
        path = candidates[-1]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data) if isinstance(data, dict) else data
    if not isinstance(events, list):
        raise ValueError(f"{path} is not a Chrome trace")
    return events


def attribute_trace(
    events: Iterable[Dict[str, Any]],
    host_infeed_s: float = 0.0,
) -> Dict[str, Any]:
    """Bucket seconds from complete ('X') trace events. The busiest
    pid/tid lane is taken as THE device lane: its busy time is classified
    by op name, and the unoccupied remainder of its span is the bubble.
    `host_infeed_s` adds externally measured host wait (e.g. telemetry's
    loader_wait_fraction x step time) on top of host-named ops."""
    lanes: Dict[Tuple[Any, Any], Dict[str, float]] = {}
    per_lane_events: Dict[Tuple[Any, Any], List] = {}
    for e in events:
        if e.get("ph", "X") != "X":
            continue
        dur = float(e.get("dur", 0.0)) / 1e6
        if dur <= 0:
            continue
        key = (e.get("pid"), e.get("tid"))
        lane = lanes.setdefault(key, {"busy": 0.0})
        lane["busy"] += dur
        per_lane_events.setdefault(key, []).append(e)
    if not lanes:
        raise ValueError("trace has no complete events to attribute")
    device_lane = max(lanes, key=lambda k: lanes[k]["busy"])
    evs = per_lane_events[device_lane]
    buckets = {b: 0.0 for b in BUCKETS}
    t_min, t_max = float("inf"), float("-inf")
    for e in evs:
        ts = float(e.get("ts", 0.0)) / 1e6
        dur = float(e.get("dur", 0.0)) / 1e6
        buckets[classify_op(str(e.get("name", "?")))] += dur
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
    span = max(t_max - t_min, 0.0)
    busy = sum(buckets.values())
    buckets["bubble"] = max(span - busy, 0.0)
    buckets["host_infeed"] += max(float(host_infeed_s), 0.0)
    total = sum(buckets.values())
    return {
        "source": "trace",
        "device_lane": {"pid": device_lane[0], "tid": device_lane[1],
                        "events": len(evs)},
        "span_s": span,
        "step_time_s": total,
        "buckets": _fractions(buckets, total),
    }


# ------------------------------------------------------------ cost-model side
def lower_step_programs(cfg, batch: Optional[int] = None):
    """Lower (NOT compile) the production step program(s) for `cfg` at
    `batch`: {"trunk", "bank"} under async-bank configs, {"step"} for the
    monolithic one. Shapes only (the state is `eval_shape`d). The ONE
    lowering both `step_costs` (which compiles for XLA's cost analysis)
    and `step_byte_model` (which parses the lowered StableHLO — no compile)
    consume, so the two byte sources can never describe different programs.
    Returns (programs dict, info dict)."""
    import jax
    import jax.numpy as jnp

    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.perf.planner import lower_split_programs

    trainer = Trainer(cfg, steps_per_epoch=100, donate=True)
    state = jax.eval_shape(
        lambda rng: trainer.init_state(rng, for_restore=True),
        jax.random.PRNGKey(0),
    )
    m = cfg.model
    b = int(batch) if batch else int(cfg.data.train_batch_size)
    img_dtype = jnp.uint8 if trainer._device_augment else jnp.float32
    images = jax.ShapeDtypeStruct((b, m.img_size, m.img_size, 3), img_dtype)
    labels = jax.ShapeDtypeStruct((b,), jnp.int32)
    seeds = jax.ShapeDtypeStruct((b,), jnp.uint32)
    use_mine = jnp.asarray(1.0, jnp.float32)
    update_gmm = jnp.asarray(True, bool)

    programs: Dict[str, Any] = {}
    if trainer.async_bank:
        trunk_l, bank_l = lower_split_programs(
            trainer, state, images, labels, seeds, use_mine, update_gmm
        )
        programs["trunk"] = trunk_l
        programs["bank"] = bank_l
    else:
        programs["step"] = trainer._train_step.lower(
            state, images, labels, seeds, use_mine, update_gmm, warm=False,
        )
    info = {
        "batch": b,
        "backend": jax.default_backend(),
        "async_bank": trainer.async_bank,
        "compute_dtype": cfg.model.compute_dtype,
    }
    return programs, info


def step_costs(cfg, batch: Optional[int] = None,
               lowered=None) -> Dict[str, Any]:
    """FLOPs / bytes-accessed / peak-bytes of the production step program(s)
    for `cfg` at `batch` (per-chip), from XLA's compiled-module analyses —
    hermetic on CPU. Async-bank configs report trunk + bank separately and
    summed; sync configs the monolithic step. Shapes only: the state is
    `eval_shape`d, nothing real is allocated. Also the `cost_provider`
    behind ProfilerWindow's off-TPU fallback capture.

    `lowered` takes a pre-built `lower_step_programs(cfg, batch)` result so
    a caller that also runs `step_byte_model` (trace_report, bench
    --measure dtype) traces the flagship step ONCE, not per consumer."""
    from mgproto_tpu.perf.planner import _program_peak

    def _costs(compiled) -> Dict[str, Any]:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        peak, _ = _program_peak(compiled)
        return {
            "flops": float(ca.get("flops") or 0.0),
            "bytes_accessed": float(
                ca.get("bytes accessed", ca.get("bytes_accessed")) or 0.0
            ),
            "peak_bytes": int(peak),
        }

    programs_lowered, info = (
        lowered if lowered is not None else lower_step_programs(cfg, batch)
    )
    programs = {
        name: _costs(low.compile())
        for name, low in programs_lowered.items()
    }
    return {
        "batch": info["batch"],
        "backend": info["backend"],
        "async_bank": info["async_bank"],
        "programs": programs,
        "flops": sum(p["flops"] for p in programs.values()),
        "bytes_accessed": sum(
            p["bytes_accessed"] for p in programs.values()
        ),
        "peak_bytes": sum(p["peak_bytes"] for p in programs.values()),
    }


# ---------------------------------------------- dtype-aware HLO byte model
# XLA's compiled-module `bytes accessed` is the committed stall reports'
# historical byte source, but it has two blind spots the mixed-precision
# work exposes: (1) CPU float-normalization rewrites bf16 programs into
# f32-with-converts, so a bf16 flagship REPORTS MORE bytes on the CPU
# fallback while moving half the bytes on TPU; (2) CPU fusion is far less
# aggressive than TPU's, so the totals are pessimistic (the committed
# b256 report is `hbm_model_clamped` for exactly this reason). This model
# instead walks the PRE-OPTIMIZATION StableHLO — where every tensor still
# carries its LOGICAL dtype (bf16 stays 2 bytes) and shapes are backend-
# neutral, the same artifact scripts/mfu_headroom.py reads — and charges
# each op its operand + result bytes. Two totals come out:
#
#   raw_bytes    every op charged — the UNFUSED view. This is what a
#                fusion kills, so the top_byte_movers ranking uses it:
#                the #1 row is the next kernel to write.
#   fused_bytes  only "memory-major" ops charged (conv/dot/reduce/gather/
#                scatter/sort/custom_call/concat/dus); elementwise, casts,
#                broadcasts, transposes and pads are assumed fused into a
#                neighboring major op's read or write — the IDEAL-FUSION
#                floor a TPU-class compiler (or the Pallas epilogue
#                kernels) approaches. The roofline's HBM bucket uses this.
#
# Known approximations (deliberate, documented): both branches of a
# lax.cond count (like XLA's own cost analysis); a multiply-called helper
# function counts once; while-loop bodies count one trip. All are shared
# by the f32 and bf16 walks, so the dtype RATIO — the number the
# acceptance gates on — is clean.
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_HLO_OP_RE = re.compile(r"=\s+\"?([A-Za-z_][\w]*\.[\w]+)")
_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1,
    "index": 8,
    "complex<f32>": 8, "complex<f64>": 16,
}
# ops whose operand/result traffic survives ideal fusion (everything else
# is an elementwise/layout op a TPU-class fusion pass folds into these)
_MAJOR_OPS = frozenset((
    "convolution", "dot_general", "dot", "reduce", "reduce_window",
    "select_and_scatter", "gather", "scatter", "dynamic_slice",
    "dynamic_update_slice", "sort", "custom_call", "concatenate",
    "while", "rng_bit_generator", "fft", "cholesky", "triangular_solve",
))


def _tensor_nbytes(sig: str) -> int:
    """Bytes of one `tensor<...>` signature ('256x112x112x64xbf16',
    'f32', '2xindex'). Unknown element types charge 4 bytes."""
    parts = sig.split("x")
    dtype = parts[-1]
    n = 1
    for p in parts[:-1]:
        if not p.isdigit():  # dynamic/symbolic dims: charge as 1
            continue
        n *= int(p)
    return n * _HLO_DTYPE_BYTES.get(dtype, 4)


# ops a fusing compiler folds into the consumer that reads them: charging
# a major op's operand THROUGH these at the source signature models e.g. a
# reduce over convert(bf16 -> f32) as reading the bf16 bytes (accumulation
# is in-register f32) — exactly what TPU fusion emits for the f32
# BatchNorm statistics over a bf16 trunk
_FOLDABLE_OPS = frozenset((
    "convert", "reshape", "transpose", "bitcast_convert",
))
_OPERAND_RE = re.compile(r"%[A-Za-z0-9_#.]+")
_RESULT_RE = re.compile(r"^\s*(%[A-Za-z0-9_#.]+)\s*=")


def _fold_operand(name: str, defs: Dict[str, Tuple], sig: str,
                  depth: int = 8) -> str:
    """Follow `name` back through foldable producers; the signature at the
    chain's head is what a fused consumer actually streams from memory."""
    while depth > 0:
        d = defs.get(name)
        if d is None:
            return sig
        op_short, operands, op_types, _ = d
        if op_short not in _FOLDABLE_OPS or not operands or not op_types:
            return sig
        # the foldable op's own input: what a fused reader would stream
        sig = op_types[0]
        name = operands[0]
        depth -= 1
    return sig


def parse_hlo_bytes(text: str) -> Dict[str, Any]:
    """Per-op byte charges from a pre-optimization StableHLO module (see the
    model notes above). Returns {"raw_bytes", "fused_bytes", "ops": {key ->
    {"op", "result", "count", "bytes", "fused_bytes", "fused"}}} where key
    groups identical (op kind, result signature) pairs. The raw view
    charges every op exactly as written; the fused view charges only major
    ops, with operands folded through convert/reshape/transpose chains to
    the signature a fused reader would stream from memory."""
    # pass 1: def sites — %name -> (short op, operands, op types, result)
    defs: Dict[str, Tuple] = {}
    parsed_lines = []
    for line in text.splitlines():
        stripped = line.strip()
        if (
            not stripped.startswith("%")
            and not stripped.startswith("stablehlo.")
        ):
            # func/module headers, returns, braces: their tensors are
            # charged at the ops that actually read/write them
            continue
        m = _HLO_OP_RE.search(line) if "=" in line else None
        if m is None:
            continue
        op = m.group(1)
        short = op.rsplit(".", 1)[-1]
        sig_at = line.rfind(" : ")
        if sig_at < 0:
            continue
        sig = line[sig_at + 3:]
        body = line[m.end(): sig_at]
        operands = _OPERAND_RE.findall(body)
        if "->" in sig:
            op_types = _TENSOR_RE.findall(sig.split("->", 1)[0])
            res_types = _TENSOR_RE.findall(sig.split("->", 1)[1])
        else:
            listed = _TENSOR_RE.findall(sig)
            # short elementwise form ('add %a, %b : tensor<T>'): the last
            # listed type is the result; operands take the listed types in
            # order, unlisted ones sharing the last — truncated to the real
            # operand count (a zero-operand constant/iota charges its
            # result ONCE, not as a phantom operand too)
            if listed:
                op_types = (
                    listed + [listed[-1]] * max(
                        len(operands) - len(listed), 0
                    )
                )[: len(operands)]
                res_types = listed[-1:]
            else:
                op_types, res_types = [], []
        if not res_types:
            continue
        rm = _RESULT_RE.match(line)
        if rm is not None:
            defs[rm.group(1)] = (short, operands, op_types, res_types[-1])
        parsed_lines.append((op, short, operands, op_types, res_types))

    # pass 2: charges
    raw_total = 0.0
    fused_total = 0.0
    ops: Dict[str, Dict[str, Any]] = {}
    for op, short, operands, op_types, res_types in parsed_lines:
        raw = sum(_tensor_nbytes(t) for t in op_types) + sum(
            _tensor_nbytes(t) for t in res_types
        )
        is_major = short in _MAJOR_OPS
        fused = 0.0
        if is_major:
            fused = sum(_tensor_nbytes(t) for t in res_types)
            for i, t in enumerate(op_types):
                name = operands[i] if i < len(operands) else None
                folded = _fold_operand(name, defs, t) if name else t
                # a fold can only shrink what the fused reader streams
                fused += min(_tensor_nbytes(folded), _tensor_nbytes(t))
        raw_total += raw
        fused_total += fused
        result = res_types[-1]
        key = f"{op} -> tensor<{result}>"
        row = ops.setdefault(key, {
            "op": op, "result": result, "count": 0, "bytes": 0.0,
            "fused_bytes": 0.0, "fused": is_major,
        })
        row["count"] += 1
        row["bytes"] += raw
        row["fused_bytes"] += fused
    return {
        "raw_bytes": raw_total,
        "fused_bytes": fused_total,
        "ops": ops,
    }


def _mover_rows(ops: Dict[str, Dict[str, Any]], total: float,
                top_n: int) -> List[Dict[str, Any]]:
    rows = []
    for key, row in sorted(
        ops.items(), key=lambda kv: kv[1]["bytes"], reverse=True
    )[: max(top_n, 0)]:
        short = row["op"].rsplit(".", 1)[-1].replace("_", "-")
        rows.append({
            "name": key,
            "bucket": classify_op(short),
            "count": int(row["count"]),
            "bytes_accessed": float(row["bytes"]),
            "bytes_fraction": (
                float(row["bytes"]) / total if total > 0 else 0.0
            ),
            "seconds": None,
            "time_fraction": None,
        })
    return rows


def step_byte_model(cfg, batch: Optional[int] = None,
                    top_n: int = 12, lowered=None) -> Dict[str, Any]:
    """The dtype-aware byte model of the production step program(s): lowers
    (never compiles) through `lower_step_programs` and walks the StableHLO.
    Returns totals (raw + ideal-fusion views), per-program splits, and the
    ranked `top_byte_movers` table — the fusion work list. `lowered`
    shares a pre-built lowering, as in `step_costs`."""
    lowered, info = (
        lowered if lowered is not None else lower_step_programs(cfg, batch)
    )
    per_program: Dict[str, Dict[str, float]] = {}
    merged: Dict[str, Dict[str, Any]] = {}
    raw_total = 0.0
    fused_total = 0.0
    for name, low in lowered.items():
        parsed = parse_hlo_bytes(low.as_text())
        per_program[name] = {
            "raw_bytes": parsed["raw_bytes"],
            "fused_bytes": parsed["fused_bytes"],
        }
        raw_total += parsed["raw_bytes"]
        fused_total += parsed["fused_bytes"]
        for key, row in parsed["ops"].items():
            agg = merged.setdefault(
                key, dict(row, count=0, bytes=0.0, fused_bytes=0.0)
            )
            agg["count"] += row["count"]
            agg["bytes"] += row["bytes"]
            agg["fused_bytes"] += row["fused_bytes"]
    return {
        "byte_model": "hlo_dtype",
        **info,
        "raw_bytes": raw_total,
        "fused_bytes": fused_total,
        "programs": per_program,
        "top_byte_movers": {
            "source": "hlo_model",
            "total_bytes": raw_total,
            "rows": _mover_rows(merged, raw_total, top_n),
        },
    }


def top_byte_movers_from_trace(
    events: Iterable[Dict[str, Any]], top_n: int = 12
) -> Dict[str, Any]:
    """The ranked byte-movers table from a captured device trace: device-op
    events on the busiest lane grouped by name, ranked by `bytes_accessed`
    from the event args when the profiler recorded it, by duration
    otherwise (bytes then stay null rather than invented). Same row schema
    as the hlo_model source, so the committed-report guard covers both."""
    lanes: Dict[Tuple[Any, Any], float] = {}
    per_lane: Dict[Tuple[Any, Any], List] = {}
    for e in events:
        if e.get("ph", "X") != "X":
            continue
        dur = float(e.get("dur", 0.0)) / 1e6
        if dur <= 0:
            continue
        key = (e.get("pid"), e.get("tid"))
        lanes[key] = lanes.get(key, 0.0) + dur
        per_lane.setdefault(key, []).append(e)
    if not lanes:
        return {"source": "trace", "total_bytes": None, "rows": []}
    device_lane = max(lanes, key=lanes.get)
    busy = lanes[device_lane]
    groups: Dict[str, Dict[str, Any]] = {}
    for e in per_lane[device_lane]:
        name = str(e.get("name", "?"))
        args = e.get("args") or {}
        b = args.get("bytes_accessed", args.get("bytes accessed"))
        g = groups.setdefault(name, {"count": 0, "seconds": 0.0,
                                     "bytes": None})
        g["count"] += 1
        g["seconds"] += float(e.get("dur", 0.0)) / 1e6
        if b is not None:
            g["bytes"] = (g["bytes"] or 0.0) + float(b)
    known = [g["bytes"] for g in groups.values() if g["bytes"] is not None]
    total_bytes = sum(known) if known else None
    rows = []
    for name, g in sorted(
        groups.items(),
        key=lambda kv: (
            kv[1]["bytes"] if kv[1]["bytes"] is not None else -1.0,
            kv[1]["seconds"],
        ),
        reverse=True,
    )[: max(top_n, 0)]:
        rows.append({
            "name": name,
            "bucket": classify_op(name),
            "count": int(g["count"]),
            "bytes_accessed": g["bytes"],
            "bytes_fraction": (
                g["bytes"] / total_bytes
                if g["bytes"] is not None and total_bytes else None
            ),
            "seconds": g["seconds"],
            "time_fraction": g["seconds"] / busy if busy > 0 else 0.0,
        })
    return {"source": "trace", "total_bytes": total_bytes, "rows": rows}


def roofline_buckets(
    flops: float,
    bytes_accessed: float,
    step_time_s: Optional[float] = None,
    host_infeed_s: float = 0.0,
    collective_wait_s: float = 0.0,
    peak_flops: float = DEFAULT_PEAK_FLOPS,
    hbm_bytes_per_s: float = DEFAULT_HBM_BYTES_PER_S,
) -> Dict[str, Any]:
    """Apportion a step via the roofline: compute time is flops/peak, the
    HBM bucket is the bandwidth time compute cannot hide, host and
    collective time are whatever the caller measured (`collective_wait_s`
    is e.g. telemetry's per-step barrier+collective wait; the single-host
    cost fallback passes nothing and the line item reports ZERO, keeping
    the schema identical across fleet sizes).

    A MEASURED `step_time_s` is GROUND TRUTH: the buckets partition it
    exactly. The bandwidth model is an upper bound on stall time (XLA's
    bytes-accessed is fusion-pessimistic, especially on the CPU backend),
    so the HBM bucket is clamped into the measured residual after compute,
    host and collective time; whatever the bandwidth model cannot claim is
    the bubble. `hbm_model_clamped` flags when the clamp bit (the model had
    MORE traffic than the residual — read the HBM bucket as "at least
    this bound-ness", not a precise stall count). Without a measurement
    the modeled sum stands in (bubble 0) and the report says so.
    Fractions always sum to 1 of the reported step time."""
    mxu_s = flops / peak_flops if peak_flops > 0 else 0.0
    hbm_total_s = bytes_accessed / hbm_bytes_per_s if hbm_bytes_per_s else 0.0
    hbm_raw_s = max(hbm_total_s - mxu_s, 0.0)
    host_s = max(float(host_infeed_s), 0.0)
    coll_s = max(float(collective_wait_s), 0.0)
    measured = step_time_s is not None
    floor = mxu_s + host_s + coll_s
    if measured:
        # a step cannot be shorter than its compute + host + collective
        # floor; a measurement below it means the peaks are mis-set, and
        # the floor wins so the partition stays consistent
        total = max(float(step_time_s), floor)
        hbm_s = min(hbm_raw_s, max(total - floor, 0.0))
    else:
        total = floor + hbm_raw_s
        hbm_s = hbm_raw_s
    buckets = {
        "mxu_busy": mxu_s,
        "hbm_bound": hbm_s,
        "collective_wait": coll_s,
        "host_infeed": host_s,
        "bubble": max(total - mxu_s - hbm_s - host_s - coll_s, 0.0),
    }
    return {
        "source": "cost_analysis",
        "step_time_s": total,
        "step_time_measured": measured,
        "modeled_step_time_s": floor + hbm_raw_s,
        "hbm_total_s": hbm_total_s,
        "hbm_model_clamped": measured and hbm_raw_s > hbm_s,
        "buckets": _fractions(buckets, total),
    }


# ------------------------------------------------------------------- report
def _fractions(buckets: Dict[str, float], total: float) -> Dict[str, Any]:
    return {
        name: {
            "seconds": buckets[name],
            "fraction": buckets[name] / total if total > 0 else 0.0,
        }
        for name in BUCKETS
    }


def attainable_mfu_default(repo_root: Optional[str] = None) -> float:
    """The committed structural ceiling (mfu_headroom's FLOP-weighted MXU
    tiling bound), falling back to the PERF.md constant."""
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    path = os.path.join(root, "evidence", "mfu_headroom_b256.json")
    try:
        with open(path) as f:
            v = json.load(f).get("flop_weighted_mxu_eff_bound")
        if v:
            return float(v)
    except (OSError, ValueError):
        pass
    return DEFAULT_ATTAINABLE_MFU


def finish_report(
    attribution: Dict[str, Any],
    flops: Optional[float] = None,
    peak_flops: float = DEFAULT_PEAK_FLOPS,
    attainable_mfu: Optional[float] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Wrap a bucket attribution into the one stall-report schema: add the
    fraction-sum self-check and the measured-vs-attainable MFU line items
    (PERF.md decomposition: measured = flops / (step x peak), attainable =
    the array-padding ceiling, ratio = the stall tax the buckets itemize)."""
    report: Dict[str, Any] = {"stall_report": True, **attribution}
    fractions = [
        b["fraction"] for b in attribution["buckets"].values()
    ]
    report["fraction_sum"] = sum(fractions)
    att = (
        float(attainable_mfu) if attainable_mfu is not None
        else attainable_mfu_default()
    )
    report["attainable_mfu"] = att
    step = attribution.get("step_time_s") or 0.0
    if flops and step > 0 and peak_flops > 0:
        measured = flops / (step * peak_flops)
        report["flops"] = flops
        report["peak_flops"] = peak_flops
        report["measured_mfu"] = measured
        report["mfu_ratio_measured_over_attainable"] = (
            measured / att if att > 0 else None
        )
    if extra:
        report.update(extra)
    return report
