"""Flight recorder: a bounded ring of recent structured events, dumped on
failure.

Post-mortems of a crashed or rolled-back run keep asking the same question:
what was the system DOING in the seconds before it went wrong? Metrics are
aggregates and the log is prose; the flight recorder keeps the last N
structured events — train steps, micro-batch dispatch triggers, breaker
transitions, replica failures/restarts, hot swaps, chaos injections,
divergence streaks — and writes them as JSONL exactly when something dies:

  * divergence rollback / preemption / unhandled crash (cli/train.py)
  * replica death or wedge detection (serving/replica.py)

Recording is always on and deliberately cheap (one small dict appended to a
`deque(maxlen=...)` under a lock — the ring IS the retention policy); the
DUMP only happens when a `dump_dir` has been configured, so library users
and tests pay zero IO. The process-current recorder follows the same
install/restore pattern as the telemetry registry and tracer.

Events are host-side plain data; callers must `device_get` anything device-
resident first (same contract as the metric registry).

Multi-host identity (ISSUE 10): every event and dump header carries this
process's fleet index (`host` = jax.process_index, `pid` = OS pid), and a
non-zero host's dump files take a `.h<host>` suffix
(`flightrec_<reason>_<n>.h<host>.jsonl`, the PR-9 log-suffix convention) —
so a pod-wide PEER_LOST dump into the shared telemetry dir yields one
mergeable, attributable file per host instead of an overwrite race.
`mgproto-telemetry fleet` lists the dumps per host. Single process resolves
to host 0: unsuffixed names, exactly the pre-fleet behavior.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from mgproto_tpu.telemetry.tracing import _jsonable

DEFAULT_CAPACITY = 512


def _default_host() -> int:
    """This process's fleet index (the shared telemetry.session definition:
    best-effort, host 0 in jax-free processes)."""
    from mgproto_tpu.telemetry.session import resolve_host

    return resolve_host()


class FlightRecorder:
    """Ring buffer of recent events + dump-to-JSONL on failure."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        clock=time.time,
        dump_dir: Optional[str] = None,
        host: Optional[int] = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.clock = clock
        self.dump_dir = dump_dir
        self.host = _default_host() if host is None else int(host)
        self.pid = os.getpid()
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0  # total events recorded (survives ring eviction)
        self._dumps = 0
        self.dumped: List[str] = []  # paths written by maybe_dump

    # ----------------------------------------------------------------- record
    def record(self, kind: str, **fields) -> None:
        """Append one event. Fields must be JSON-able scalars (everything
        else is stringified, like span attrs)."""
        evt: Dict[str, Any] = {
            "ts": float(self.clock()),
            "kind": str(kind),
            "host": self.host,
            "pid": self.pid,
        }
        for k, v in fields.items():
            evt[k] = _jsonable(v)
        with self._lock:
            evt["seq"] = self._seq
            self._seq += 1
            self._events.append(evt)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    @property
    def recorded_total(self) -> int:
        with self._lock:
            return self._seq

    # ------------------------------------------------------------------- dump
    def dump(self, path: str, reason: str) -> str:
        """Write the ring as JSONL: one header record (reason, wall time,
        counts), then one line per event, oldest first. Atomic (tmp+rename)
        so a crash during the dump never leaves a torn file for the
        post-mortem that needs it most."""
        events = self.events()
        header = {
            "flight_recorder": True,
            "reason": str(reason),
            "dumped_at": time.time(),
            "events": len(events),
            "recorded_total": self.recorded_total,
            "capacity": self.capacity,
            "host": self.host,
            "pid": self.pid,
        }
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for evt in events:
                f.write(json.dumps(evt) + "\n")
        os.replace(tmp, path)
        return path

    def maybe_dump(self, reason: str) -> Optional[str]:
        """Dump iff a `dump_dir` is configured (the failure hooks call this
        unconditionally; a library/test process without a configured dir
        pays nothing). Each dump gets its own numbered file so a rollback
        storm cannot overwrite the first — usually most interesting —
        capture."""
        if not self.dump_dir:
            return None
        with self._lock:
            n = self._dumps
            self._dumps += 1
        # host 0 keeps the unsuffixed name; other hosts suffix theirs so a
        # pod-wide dump into the shared telemetry dir never collides
        suffix = f".h{self.host}" if self.host > 0 else ""
        path = os.path.join(
            self.dump_dir, f"flightrec_{reason}_{n:03d}{suffix}.jsonl"
        )
        out = self.dump(path, reason)
        self.dumped.append(out)
        return out


_DEFAULT = FlightRecorder()
_CURRENT = _DEFAULT


def get_recorder() -> FlightRecorder:
    """The process-current recorder (always exists; dump_dir may be None)."""
    return _CURRENT


def set_recorder(recorder: Optional[FlightRecorder]) -> FlightRecorder:
    """Install `recorder` as process-current (None -> the process default);
    returns the previously current one so callers can restore it."""
    global _CURRENT
    prev = _CURRENT
    _CURRENT = recorder if recorder is not None else _DEFAULT
    return prev


def record_event(kind: str, **fields) -> None:
    """One-liner for instrumentation sites: record on the current ring."""
    _CURRENT.record(kind, **fields)
