"""Programmatic profiler windows: capture the trace exactly when it matters.

ROADMAP item 2's first lever is "capture the pending profiler trace and
apportion the stall budget" — but a trace captured at an arbitrary moment
usually misses the anomaly it was meant to explain. `ProfilerWindow` arms
`jax.profiler` trace capture either

  * for a CONFIGURED STEP RANGE (`--profile_steps A:B`, e.g. steady state
    well past warmup), or
  * AUTOMATICALLY when a trigger fires (`--profile_on_anomaly`):
      - step-time spike: a step slower than `spike_factor` x the window's
        own EMA (after `min_steps` of settling),
      - recompile: the watched StepMonitor's `jit_recompiles_total` grew
        mid-run (steady state must be zero-recompile; any growth is
        exactly the moment to capture),
      - loader-wait: the step blocked on the input pipeline for more than
        `wait_fraction` of its wall time.

A step-range capture is ONE window spanning the whole range (a bare step
captures one step); anomaly captures each run `capture_steps` steps. Every
capture writes one directory under `out_dir` (`trace_<reason>_step<N>/`),
with at most `max_captures` anomaly captures per run and a
`cooldown_steps` refractory period so a pathological run cannot spend its
epoch writing traces.

OFF-TPU DEGRADE: `jax.profiler` traces on CPU carry no device lanes worth
attributing, so off-TPU (or when `start_trace` raises) the window degrades
to a COST-ANALYSIS-ONLY capture: the `cost_provider` callable (the caller
lowers its actual production program — see `obs/stall.py::step_costs`)
is invoked once and its FLOPs/bytes report is written as
`cost_analysis.json` next to a `capture_meta.json` describing why the
window armed. That keeps the whole arm/disarm/trigger path tier-1 testable
and still yields the numbers `scripts/trace_report.py` attributes.

Every arm/disarm is also recorded on the flight recorder, so a post-mortem
dump shows whether (and why) a capture was in flight.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Callable, List, Optional, Tuple

from mgproto_tpu.obs.flightrec import record_event

META_FILE = "capture_meta.json"
COST_FILE = "cost_analysis.json"


def parse_step_range(raw: str) -> Optional[Tuple[int, int]]:
    """'120:130' -> (120, 130); '' -> None. A bare 'N' captures one step."""
    raw = (raw or "").strip()
    if not raw:
        return None
    start, sep, end = raw.partition(":")
    a = int(start)
    b = int(end) if sep and end else a + 1
    if b <= a:
        raise ValueError(f"empty profile step range {raw!r}")
    return a, b


def _backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "none"


def trace_supported() -> bool:
    """Real device-trace capture is only worth the IO on an accelerator."""
    return _backend() in ("tpu", "gpu")


@dataclasses.dataclass(frozen=True)
class Triggers:
    """Anomaly-trigger knobs (see module docstring)."""

    spike_factor: float = 3.0
    min_steps: int = 20  # EMA settle time before the spike trigger arms
    wait_fraction: float = 0.5
    recompile: bool = True
    ema_alpha: float = 0.1


class ProfilerWindow:
    """Step-driven capture controller; `on_step` is the only per-step hook
    (engine/train.py calls it after each observed step)."""

    def __init__(
        self,
        out_dir: str,
        steps: Optional[Tuple[int, int]] = None,
        on_anomaly: bool = False,
        triggers: Optional[Triggers] = None,
        capture_steps: int = 3,
        max_captures: int = 2,
        cooldown_steps: int = 50,
        monitor=None,
        cost_provider: Optional[Callable[[], dict]] = None,
        log: Optional[Callable[[str], None]] = None,
    ):
        self.out_dir = out_dir
        self.steps = steps
        self.on_anomaly = bool(on_anomaly)
        self.triggers = triggers if triggers is not None else Triggers()
        self.capture_steps = max(int(capture_steps), 1)
        self.max_captures = max(int(max_captures), 1)
        self.cooldown_steps = max(int(cooldown_steps), 0)
        self.monitor = monitor
        self.cost_provider = cost_provider
        self.log = log
        self.captures: List[dict] = []  # {dir, reason, step, fallback}
        self._step = 0  # steps observed by THIS window (this invocation)
        self._ema: Optional[float] = None
        self._armed_reason: Optional[str] = None
        self._armed_at = 0
        self._tracing = False  # a real jax.profiler trace is open
        self._cooldown_until = -1
        self._last_recompiles = (
            monitor.recompile_count if monitor is not None else 0
        )

    # ------------------------------------------------------------------ state
    @property
    def armed(self) -> bool:
        return self._armed_reason is not None

    @property
    def steps_observed(self) -> int:
        return self._step

    # ------------------------------------------------------------------- hook
    def on_step(self, seconds: float, wait_fraction: float = 0.0) -> None:
        """Observe one completed step; decides arm/disarm. `seconds` is the
        step's host wall time, `wait_fraction` the loader-blocked share of
        it. Step indices count THIS window's observations (a resumed run
        restarts at 0 — document ranges accordingly)."""
        step = self._step
        self._step += 1

        if self.armed:
            # an explicit step range is ONE window: it stays open until the
            # range ends (never fragmented into capture_steps-long pieces);
            # anomaly windows run capture_steps steps
            if self._armed_reason == "steps":
                if self.steps is None or step >= self.steps[1]:
                    self.disarm()
            elif step - self._armed_at + 1 >= self.capture_steps:
                self.disarm()
            return

        reason = self._due(step, seconds, wait_fraction)
        # EMA updates AFTER the spike check so the spike that arms the
        # window does not immediately poison its own baseline
        a = self.triggers.ema_alpha
        self._ema = (
            seconds if self._ema is None
            else a * seconds + (1 - a) * self._ema
        )
        if reason is not None:
            self.arm(reason)

    def _due(
        self, step: int, seconds: float, wait_fraction: float
    ) -> Optional[str]:
        if self.steps is not None and self.steps[0] <= step < self.steps[1]:
            return "steps"
        if not self.on_anomaly:
            return None
        if len(self.captures) >= self.max_captures:
            return None
        if step < self._cooldown_until:
            return None
        t = self.triggers
        if t.recompile and self.monitor is not None:
            count = self.monitor.recompile_count
            if count > self._last_recompiles:
                self._last_recompiles = count
                return "recompile"
            self._last_recompiles = count
        if (
            self._ema is not None
            and step >= t.min_steps
            and seconds > t.spike_factor * self._ema
        ):
            return "spike"
        if wait_fraction >= t.wait_fraction and step >= t.min_steps:
            return "loader_wait"
        return None

    # ----------------------------------------------------------- arm / disarm
    def arm(self, reason: str) -> str:
        """Open a capture window NOW (also the public entry for one-shot
        captures, e.g. serve warmup). Returns the capture directory."""
        if self.armed:
            return self.captures[-1]["dir"]
        path = os.path.join(
            self.out_dir, f"trace_{reason}_step{self._step:06d}"
        )
        os.makedirs(path, exist_ok=True)
        self._armed_reason = reason
        self._armed_at = self._step
        fallback = True
        if trace_supported():
            try:
                import jax

                jax.profiler.start_trace(path)
                self._tracing = True
                fallback = False
            except Exception as e:  # plugin missing, second trace, ...
                if self.log:
                    self.log(f"profiler: start_trace failed ({e}); "
                             "falling back to cost analysis")
        capture = {
            "dir": path,
            "reason": reason,
            "step": self._step,
            "fallback": fallback,
        }
        self.captures.append(capture)
        record_event(
            "profiler_arm", reason=reason, step=self._step, dir=path,
            fallback=fallback,
        )
        if self.log:
            self.log(
                f"profiler: armed ({reason}) at step {self._step} -> {path}"
            )
        self._write_meta(capture)
        if fallback:
            self._write_cost_analysis(capture)
        return path

    def disarm(self) -> None:
        """Close the open window (stop the device trace if one is live)."""
        if not self.armed:
            return
        reason = self._armed_reason
        self._armed_reason = None
        self._cooldown_until = self._step + self.cooldown_steps
        if self._tracing:
            self._tracing = False
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:
                if self.log:
                    self.log(f"profiler: stop_trace failed ({e})")
        record_event("profiler_disarm", reason=reason, step=self._step)
        if self.log:
            self.log(f"profiler: capture closed at step {self._step}")

    def close(self) -> None:
        """End-of-run safety: never leave a device trace open."""
        self.disarm()

    # -------------------------------------------------------------- fallbacks
    def _write_meta(self, capture: dict) -> None:
        meta = {
            "profiler_capture": True,
            "reason": capture["reason"],
            "step": capture["step"],
            "backend": _backend(),
            "fallback": capture["fallback"],
            "capture_steps": self.capture_steps,
            "wall_time": time.time(),
        }
        with open(os.path.join(capture["dir"], META_FILE), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)

    def _write_cost_analysis(self, capture: dict) -> None:
        """The off-TPU degrade: one cost/memory-analysis report of the
        production program, so the capture still feeds trace_report's
        roofline attribution."""
        if self.cost_provider is None:
            return
        try:
            costs = self.cost_provider()
        except Exception as e:
            costs = {"error": f"{type(e).__name__}: {e}"}
            if self.log:
                self.log(f"profiler: cost_provider failed ({e})")
        with open(os.path.join(capture["dir"], COST_FILE), "w") as f:
            json.dump(costs, f, indent=2, sort_keys=True)


@contextlib.contextmanager
def profile_block(
    out_dir: str,
    cost_provider: Optional[Callable[[], dict]] = None,
    reason: str = "block",
    log: Optional[Callable[[str], None]] = None,
):
    """One-shot capture around a block (serve warmup uses this): a real
    device trace on TPU/GPU, the cost-analysis fallback elsewhere. No-op
    when `out_dir` is falsy."""
    if not out_dir:
        yield None
        return
    window = ProfilerWindow(
        out_dir, cost_provider=cost_provider, log=log
    )
    path = window.arm(reason)
    try:
        yield path
    finally:
        window.disarm()
