// Native host-side batch-assembly kernels for the mgproto-tpu input pipeline.
//
// The reference's data layer decodes and converts every image on the Python
// main thread (reference main.py:94 num_workers=0; SURVEY.md §7.3.6
// "bottleneck-by-neglect"). Our loader already overlaps PIL decode on a
// thread pool; this library removes the remaining per-image Python cost: the
// uint8 HWC -> normalized float32 conversion, which in numpy is four
// GIL-dispatched array passes ((x/255 - mean)/std) per image. Here it is one
// fused pass using three 256-entry per-channel lookup tables, plus a
// std::thread-parallel batched variant for whole-batch assembly.
//
// Exposed via ctypes (no pybind11 in the image); see mgproto_tpu/native.

#include <cstddef>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

// Build per-channel LUTs: lut[c][v] = v * scale[c] + bias[c].
// With scale = 1/(255*std) and bias = -mean/std this is exactly
// (v/255 - mean)/std up to f32 rounding.
inline void build_luts(const float* scale, const float* bias, float lut[3][256]) {
  for (int c = 0; c < 3; ++c) {
    for (int v = 0; v < 256; ++v) {
      lut[c][v] = static_cast<float>(v) * scale[c] + bias[c];
    }
  }
}

inline void convert_px(const uint8_t* src, int64_t n_px,
                       const float lut[3][256], float* out) {
  for (int64_t i = 0; i < n_px; ++i) {
    const uint8_t* p = src + 3 * i;
    float* q = out + 3 * i;
    q[0] = lut[0][p[0]];
    q[1] = lut[1][p[1]];
    q[2] = lut[2][p[2]];
  }
}

}  // namespace

extern "C" {

// Fused (u8/255 - mean)/std for one [n_px, 3] interleaved HWC image.
// scale[3] = 1/(255*std), bias[3] = -mean/std (precomputed by the caller).
void mg_u8hwc_to_f32_norm(const uint8_t* src, int64_t n_px, const float* scale,
                          const float* bias, float* out) {
  float lut[3][256];
  build_luts(scale, bias, lut);
  convert_px(src, n_px, lut, out);
}

// Plain u8 -> f32 in [0, 1] (the push pipeline is unnormalized,
// reference main.py:111-116).
void mg_u8hwc_to_f32(const uint8_t* src, int64_t n, float* out) {
  float lut[256];
  for (int v = 0; v < 256; ++v) lut[v] = static_cast<float>(v) * (1.0f / 255.0f);
  for (int64_t i = 0; i < n; ++i) out[i] = lut[src[i]];
}

// Batched, threaded variant: b images of identical [n_px, 3] shape from
// independent buffers into one contiguous [b, n_px, 3] f32 output.
void mg_batch_u8hwc_to_f32_norm(const uint8_t* const* srcs, int32_t b,
                                int64_t n_px, const float* scale,
                                const float* bias, float* out,
                                int32_t nthreads) {
  float lut[3][256];
  build_luts(scale, bias, lut);
  if (nthreads < 1) nthreads = 1;
  if (nthreads > b) nthreads = b;
  if (nthreads == 1) {
    for (int32_t i = 0; i < b; ++i)
      convert_px(srcs[i], n_px, lut, out + 3 * n_px * i);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int32_t t = 0; t < nthreads; ++t) {
    threads.emplace_back([=, &lut]() {
      for (int32_t i = t; i < b; i += nthreads)
        convert_px(srcs[i], n_px, lut, out + 3 * n_px * i);
    });
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused color-jitter kernels (train augmentation hot spot).
//
// The train pipeline's ColorJitter (reference main.py:100) was the profiled
// bulk of per-sample host cost (~42 of ~54 ms at CUB source sizes; the PIL
// HSV hue round-trip alone ~25 ms). Each kernel below is ONE pass over the
// interleaved u8 HWC image and reproduces Pillow's arithmetic BIT-EXACTLY
// (pinned by tests/test_data.py against the retained PIL oracle):
//
//   * Image.blend on u8:      float math, truncate toward zero, clip [0,255]
//   * convert("L"):           (19595 R + 38470 G + 7471 B + 0x8000) >> 16
//   * ImageStat mean:         double sum / n, then (int)(mean + 0.5)
//   * convert("HSV")/("RGB"): C float variables with double-promoted
//     expressions — written below exactly as Pillow's Convert.c does
//     (double literals force the promotion), which is what makes C the
//     natural home for this op: the numpy emulation needs an astype dance
//     per expression to mimic it, and runs slower than PIL on one core.

namespace {

inline uint8_t clip_trunc(float v) {
  int i = static_cast<int>(v);  // C cast truncates toward zero, like Pillow
  if (i < 0) return 0;
  if (i > 255) return 255;
  return static_cast<uint8_t>(i);
}

inline uint32_t luma_u8(const uint8_t* p) {
  return (19595u * p[0] + 38470u * p[1] + 7471u * p[2] + 0x8000u) >> 16;
}

}  // namespace

extern "C" {

// Brightness: blend(black, img, factor) == factor * img.
void mg_jitter_brightness(const uint8_t* src, int64_t n_px, float factor,
                          uint8_t* out) {
  for (int64_t i = 0; i < 3 * n_px; ++i) {
    out[i] = clip_trunc(factor * static_cast<float>(src[i]));
  }
}

// Contrast: blend(solid gray at round(mean(L)), img, factor).
void mg_jitter_contrast(const uint8_t* src, int64_t n_px, float factor,
                        uint8_t* out) {
  // zero-pixel guard: sum/n_px would be NaN and the float->int cast of NaN
  // is undefined behavior (ADVICE r5). Nothing to write either way.
  if (n_px <= 0) return;
  double sum = 0.0;  // ImageStat sums the integer L histogram
  for (int64_t i = 0; i < n_px; ++i) sum += luma_u8(src + 3 * i);
  const float gray =
      static_cast<float>(static_cast<int>(sum / static_cast<double>(n_px) + 0.5));
  for (int64_t i = 0; i < 3 * n_px; ++i) {
    out[i] = clip_trunc(gray + factor * (static_cast<float>(src[i]) - gray));
  }
}

// Saturation (ImageEnhance.Color): blend(L replicated to RGB, img, factor).
void mg_jitter_saturation(const uint8_t* src, int64_t n_px, float factor,
                          uint8_t* out) {
  for (int64_t i = 0; i < n_px; ++i) {
    const uint8_t* p = src + 3 * i;
    uint8_t* q = out + 3 * i;
    const float lum = static_cast<float>(luma_u8(p));
    q[0] = clip_trunc(lum + factor * (static_cast<float>(p[0]) - lum));
    q[1] = clip_trunc(lum + factor * (static_cast<float>(p[1]) - lum));
    q[2] = clip_trunc(lum + factor * (static_cast<float>(p[2]) - lum));
  }
}

// Fused RGB -> HSV -> (H + shift, u8 wraparound) -> RGB, one pass.
// Float/double mixing mirrors Pillow's Convert.c exactly (see header note).
// Every floating-point DIVISION is replaced by a lookup whose entries are
// computed with the identical expression (so bit-exactness is preserved by
// construction): divisions were ~2/3 of this kernel's per-pixel cost.
namespace {

struct HueLuts {
  float div[256][256];    // div[cr][d]  = (float)d / (float)cr       (cr>=1)
  uint8_t sat[256][256];  // sat[maxc][cr] = (uint8)(cr * 255.0 / maxc)
  int32_t sector[256];    // sector[hue] = (int)(hue * 6.0 / 255.0)
  float frac[256];        // frac[hue]   = float(fh - sector)
  float fs[256];          // fs[sat]     = (float)(sat / 255.0)
  HueLuts() {
    for (int cr = 1; cr < 256; ++cr) {
      for (int d = 0; d < 256; ++d) {
        div[cr][d] = static_cast<float>(d) / static_cast<float>(cr);
      }
    }
    for (int d = 0; d < 256; ++d) div[0][d] = 0.0f;
    for (int maxc = 1; maxc < 256; ++maxc) {
      for (int cr = 0; cr < 256; ++cr) {
        // cr > maxc never occurs for real pixels; clamp those unused
        // entries so the uint8 cast is never UB
        const double s = cr <= maxc ? cr * 255.0 / maxc : 255.0;
        sat[maxc][cr] = static_cast<uint8_t>(s);
      }
    }
    for (int cr = 0; cr < 256; ++cr) sat[0][cr] = 0;
    // hsv2rgb is PURE float arithmetic in Pillow (verified exhaustively
    // over all 2^24 HSV values): float literals here, not double
    for (int hue = 0; hue < 256; ++hue) {
      const float fh = hue * 6.0f / 255.0f;
      sector[hue] = static_cast<int>(fh);
      frac[hue] = fh - static_cast<float>(sector[hue]);
    }
    for (int s = 0; s < 256; ++s) fs[s] = s / 255.0f;
  }
};

}  // namespace

void mg_hue_shift(const uint8_t* src, int64_t n_px, int32_t shift,
                  uint8_t* out) {
  static const HueLuts lut;  // C++11 thread-safe one-time init
  for (int64_t i = 0; i < n_px; ++i) {
    const uint8_t* p = src + 3 * i;
    uint8_t* q = out + 3 * i;
    const uint8_t r = p[0], g = p[1], b = p[2];
    uint8_t umax = r > g ? r : g;
    if (b > umax) umax = b;
    uint8_t umin = r < g ? r : g;
    if (b < umin) umin = b;
    const int ucr = umax - umin;
    uint8_t hue = 0;
    const uint8_t sat = lut.sat[umax][ucr];
    if (ucr != 0) {
      const float* row = lut.div[ucr];
      const float rc = row[umax - r];
      const float gc = row[umax - g];
      const float bc = row[umax - b];
      float h;
      if (r == umax) {
        h = bc - gc;
      } else if (g == umax) {
        h = 2.0 + rc - bc;
      } else {
        h = 4.0 + gc - rc;
      }
      h = h / 6.0;
      if (h < 0.0f) h = h + 1.0;
      hue = static_cast<uint8_t>(h * 255.0);
    }
    hue = static_cast<uint8_t>(hue + shift);  // u8 wraparound = hue circle
    // hsv2rgb (sector formula; p/q/t round half-up, sector truncates)
    const int v = umax;
    if (sat == 0) {
      q[0] = q[1] = q[2] = static_cast<uint8_t>(v);
      continue;
    }
    const float maxc = umax;
    const int sector = lut.sector[hue];
    const float f = lut.frac[hue];
    const float fs = lut.fs[sat];
    const int pp = static_cast<int>(maxc * (1.0f - fs) + 0.5f);
    const int qq = static_cast<int>(maxc * (1.0f - fs * f) + 0.5f);
    const int tt = static_cast<int>(maxc * (1.0f - fs * (1.0f - f)) + 0.5f);
    switch (sector % 6) {
      case 0: q[0] = v;  q[1] = tt; q[2] = pp; break;
      case 1: q[0] = qq; q[1] = v;  q[2] = pp; break;
      case 2: q[0] = pp; q[1] = v;  q[2] = tt; break;
      case 3: q[0] = pp; q[1] = qq; q[2] = v;  break;
      case 4: q[0] = tt; q[1] = pp; q[2] = v;  break;
      default: q[0] = v; q[1] = pp; q[2] = qq; break;
    }
  }
}

}  // extern "C"
