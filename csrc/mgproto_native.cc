// Native host-side batch-assembly kernels for the mgproto-tpu input pipeline.
//
// The reference's data layer decodes and converts every image on the Python
// main thread (reference main.py:94 num_workers=0; SURVEY.md §7.3.6
// "bottleneck-by-neglect"). Our loader already overlaps PIL decode on a
// thread pool; this library removes the remaining per-image Python cost: the
// uint8 HWC -> normalized float32 conversion, which in numpy is four
// GIL-dispatched array passes ((x/255 - mean)/std) per image. Here it is one
// fused pass using three 256-entry per-channel lookup tables, plus a
// std::thread-parallel batched variant for whole-batch assembly.
//
// Exposed via ctypes (no pybind11 in the image); see mgproto_tpu/native.

#include <cstddef>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

// Build per-channel LUTs: lut[c][v] = v * scale[c] + bias[c].
// With scale = 1/(255*std) and bias = -mean/std this is exactly
// (v/255 - mean)/std up to f32 rounding.
inline void build_luts(const float* scale, const float* bias, float lut[3][256]) {
  for (int c = 0; c < 3; ++c) {
    for (int v = 0; v < 256; ++v) {
      lut[c][v] = static_cast<float>(v) * scale[c] + bias[c];
    }
  }
}

inline void convert_px(const uint8_t* src, int64_t n_px,
                       const float lut[3][256], float* out) {
  for (int64_t i = 0; i < n_px; ++i) {
    const uint8_t* p = src + 3 * i;
    float* q = out + 3 * i;
    q[0] = lut[0][p[0]];
    q[1] = lut[1][p[1]];
    q[2] = lut[2][p[2]];
  }
}

}  // namespace

extern "C" {

// Fused (u8/255 - mean)/std for one [n_px, 3] interleaved HWC image.
// scale[3] = 1/(255*std), bias[3] = -mean/std (precomputed by the caller).
void mg_u8hwc_to_f32_norm(const uint8_t* src, int64_t n_px, const float* scale,
                          const float* bias, float* out) {
  float lut[3][256];
  build_luts(scale, bias, lut);
  convert_px(src, n_px, lut, out);
}

// Plain u8 -> f32 in [0, 1] (the push pipeline is unnormalized,
// reference main.py:111-116).
void mg_u8hwc_to_f32(const uint8_t* src, int64_t n, float* out) {
  float lut[256];
  for (int v = 0; v < 256; ++v) lut[v] = static_cast<float>(v) * (1.0f / 255.0f);
  for (int64_t i = 0; i < n; ++i) out[i] = lut[src[i]];
}

// Batched, threaded variant: b images of identical [n_px, 3] shape from
// independent buffers into one contiguous [b, n_px, 3] f32 output.
void mg_batch_u8hwc_to_f32_norm(const uint8_t* const* srcs, int32_t b,
                                int64_t n_px, const float* scale,
                                const float* bias, float* out,
                                int32_t nthreads) {
  float lut[3][256];
  build_luts(scale, bias, lut);
  if (nthreads < 1) nthreads = 1;
  if (nthreads > b) nthreads = b;
  if (nthreads == 1) {
    for (int32_t i = 0; i < b; ++i)
      convert_px(srcs[i], n_px, lut, out + 3 * n_px * i);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int32_t t = 0; t < nthreads; ++t) {
    threads.emplace_back([=, &lut]() {
      for (int32_t i = t; i < b; i += nthreads)
        convert_px(srcs[i], n_px, lut, out + 3 * n_px * i);
    });
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
