"""Throughput benchmark: steady-state MGProto train step, images/sec/chip.

Measures the flagship recipe (ResNet-34 + CUB-200 shapes, batch 80 — the
reference's default, reference settings.py:22 / main.py:22) in its HEAVIEST
steady state: joint phase, mine loss on, memory enqueue on, and the EM update
fully active every iteration (reference update_interval=1, model.py:171, with
all 200 class queues full — the post-epoch-35 regime).

Both scoring paths are measured head to head (XLA matmul+top_k vs the fused
Pallas density kernel) and reported separately; the headline value is the
winner. An MFU estimate comes from the compiled step's XLA cost analysis
divided by the chip's peak bf16 FLOPs.

Output contract (BENCH_r01-r03 hardening, VERDICT r3 item 2): EVERY stdout
line is one complete, flushed JSON object, so the last line always parses —
even if an outer driver timeout SIGKILLs this process mid-attempt (the r03
failure: rc=124 after one 900s attempt left zero parseable output). Lines:

  * a start line (reads as a diagnostic if the run dies immediately),
  * one line per relay probe and per failed measurement attempt,
  * a PARTIAL result line the moment the first scoring path succeeds
    ({"metric", "value", ..., "partial": true} — a kill during the second
    path still leaves a real number as the last line),
  * the final line: the full result, or {"error", "attempts", "errors"}.

Cheap-probe gate: rounds 1-3 lost their whole bench window to relay hangs
discovered only after burning a 900s flagship attempt. Now a ~75s child
probe (mgproto_tpu/probe.py) runs first; if the backend cannot even run a
tiny matmul, bench reports that diagnostic within ~3 minutes instead.

Ladder sizing: per-attempt cap 420s, whole-run cap 900s (both env-tunable).
The pre-attempt deadline check hands a child at most the remaining budget,
so total runtime is bounded by DEADLINE_S + one child kill — sized to fit
inside the driver's observed outer window (>900s in r03).

Fault tolerance: the TPU relay this environment tunnels through refuses or
drops connections transiently (observed: `remote_compile: Connection refused`
mid-run after a successful backend init). Every measurement is wrapped in
retry-with-exponential-backoff, and each scoring path fails independently so
one broken path cannot zero out the whole bench.

`vs_baseline` compares against an ESTIMATED single-A100 throughput of the
reference PyTorch implementation (never measured in-repo, BASELINE.md:
"Throughput ... never measured"): ~350 img/s for R34-224 fwd+bwd+density —
bounded in practice by the reference's python-loop memory enqueue
(reference model.py:228-252) and python-loop EM over 200 classes
(model.py:281-298). The driver north star is >=6x that on a v5e-8
(BASELINE.json.north_star); this bench runs on ONE chip, so the per-chip
share of the north star is 6*350/8 = 262.5 img/s/chip.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

A100_EST_IMAGES_PER_SEC = 350.0
NORTH_STAR_PER_CHIP = 6 * A100_EST_IMAGES_PER_SEC / 8  # v5e-8 star, per chip

# env overrides exist so CI can smoke-test the harness at toy sizes on CPU;
# the driver runs the defaults (flagship shapes) on the real chip. Parsing
# must not throw at import time — the contract is a JSON diagnostic, never a
# bare traceback (and scripts/perf_model.py imports this module for its
# constants).
_ENV_ERRORS: list = []


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _ENV_ERRORS.append(f"{name}={raw!r} is not an integer")
        return default


BATCH = _env_int("BENCH_BATCH", 80)
WARMUP = _env_int("BENCH_WARMUP", 3)
ITERS = _env_int("BENCH_ITERS", 10)
# The 2026-07-31 on-device sweep (PERF.md) found the fused path peaks well
# above the reference's batch 80: 1016 img/s @80 -> 1169 @128 -> 1330 @256.
# A third measurement at this batch captures the throughput-optimal config;
# 0 disables it (CI smoke runs only the two reference-batch paths).
BEST_BATCH = _env_int("BENCH_BEST_BATCH", 256)
# Batch for the selective-remat attempt (`fused_b512_remat_l1`): the r4 DNF
# point, retried with layer1-only remat (ModelConfig.remat_stages) so the
# doubled activation working set fits without rematting the whole trunk.
# 0 disables the entry (CI smoke).
REMAT_BATCH = _env_int("BENCH_REMAT_BATCH", 512)
# Batch for the f32 head-to-head (`fused_f32_b256`): the bf16 flagship's
# measured counterpart (ISSUE 12). 0 disables the entry (CI smoke).
F32_BATCH = _env_int("BENCH_F32_BATCH", 256)

MAX_ATTEMPTS = 6
BACKOFF_S = (5, 10, 20, 40, 60)  # >= 5 attempts spread over >= 2 minutes
ATTEMPT_TIMEOUT_S = _env_int("BENCH_ATTEMPT_TIMEOUT_S", 420)
DEADLINE_S = _env_int("BENCH_DEADLINE_S", 900)  # whole-run cap
PROBE_TIMEOUT_S = _env_int("BENCH_PROBE_TIMEOUT_S", 75)
PROBE_ATTEMPTS = _env_int("BENCH_PROBE_ATTEMPTS", 2)
# staleness bound on the cached-fallback result (ADVICE r5): beyond this
# age a dead relay must not keep presenting an old watcher capture as a
# healthy exit — the line is still emitted (flagged "stale": true) but the
# process exits 1 so the driver sees the failure
CACHED_MAX_AGE_S = _env_int("BENCH_CACHED_MAX_AGE_S", 4 * 86400)
_START = time.monotonic()

# Each measurement attempt runs in a CHILD process: SIGALRM cannot interrupt a
# native PJRT call blocked on a wedged relay (python signal handlers only run
# at bytecode boundaries), and a half-initialized backend poisons every later
# in-process attempt. A subprocess gives a hard kill on hang and a fresh
# backend per retry.

# peak dense bf16 FLOP/s by TPU generation (public spec sheets). Public name
# (ADVICE r3): scripts/perf_model.py derives its roofline from this table.
PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}


def peak_flops(device_kind: str) -> float:
    """Peak dense bf16 FLOP/s for a jax device_kind string (public helper)."""
    kind = device_kind.lower()
    for key, peak in PEAK_BF16.items():
        if key in kind:
            return peak
    return 197e12  # default to v5e-class


def _emit(obj: dict) -> None:
    """One complete JSON object per stdout line, flushed immediately — the
    whole kill-safety contract hangs on this flush."""
    print(json.dumps(obj), flush=True)


def flagship_config(fused: bool, remat_stages: tuple = (),
                    compute_dtype: str = "bfloat16"):
    """The flagship recipe (ResNet-34, CUB-200 shapes, bf16 trunk) — the ONE
    definition compiled by both this bench and scripts/perf_model.py, so the
    analytic pre-registration in PERF.md can never drift from what is timed
    on hardware. `remat_stages` opts stages into selective remat (the
    batch-512 attempt runs layer1-only: the cheap-but-wide 112^2 stage);
    `compute_dtype` is the mixed-precision knob (perf/precision.py) — the
    flagship ships bf16, and the `fused_f32_b*` bench entry measures the
    f32 counterpart head to head so the dtype win is a BENCH line, not a
    belief."""
    from mgproto_tpu.config import Config, DataConfig, ModelConfig

    return Config(
        model=ModelConfig(
            arch="resnet34",
            num_classes=200,
            pretrained=False,
            # bf16 trunk on the MXU; params/BN-stats/density/losses stay f32
            compute_dtype=compute_dtype,
            fused_scoring=fused,
            remat_stages=tuple(remat_stages),
        ),
        # the bench feeds pre-normalized f32 random images with an inert
        # seed stream: the device augmentation tail must stay OFF even on
        # TPU (where it auto-resolves on), both for input semantics and so
        # the timed step stays comparable with pre-ISSUE-5 BENCH entries
        data=DataConfig(device_augment=False),
    )


def flops_from_cost_analysis(compiled, strict: bool = False):
    """Flop count of a compiled module, tolerating the cost_analysis return
    shapes seen across jax versions (dict, list-of-dict, None). strict=False
    returns None when unavailable (bench treats MFU as a best-effort extra);
    strict=True raises SystemExit (perf_model's flop count IS its output)."""
    err = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = ca.get("flops") if ca else None
        if f and f > 0:
            return float(f)
    except Exception as e:
        err = e
    if strict:
        raise SystemExit(
            "cost_analysis returned no usable flop count on this backend"
            + (f" (underlying error: {err!r})" if err is not None else "")
        )
    return None


def run_config(
    fused: bool, eval_mode: bool = False, remat_stages: tuple = (),
    compute_dtype: str = "bfloat16",
) -> dict:
    """Steady-state throughput for one scoring path. Returns
    {imgs_per_sec, step_time_s, flops_per_step (or None), device_kind}.

    eval_mode=True times the INFERENCE step instead (forward + mixture
    logits + log p(x), no losses/backward/EM — what a serving host runs,
    incl. via an engine/export.py artifact). Not part of the driver-contract
    plan; measure ad hoc with `python bench.py --measure eval_fused 256`.

    remat_stages selects per-stage backbone remat (the `fused_remat_l1`
    measure: layer1-only, so batch 384-512 fits without rematting the whole
    trunk — PERF.md's batch-512 DNF diagnosis)."""
    if os.environ.get("BENCH_FAIL_INJECT"):
        # deterministic, instant child failure for the contract tests: fires
        # before any jax/model work so the retry ladder is cheap to exercise
        raise RuntimeError("BENCH_FAIL_INJECT: simulated child failure")
    if os.environ.get("BENCH_HANG_INJECT"):
        # deterministic child hang for the kill-mid-attempt contract test;
        # bounded sleep so an orphaned child cannot linger past the test
        time.sleep(_env_int("BENCH_HANG_INJECT_S", 120))
        raise RuntimeError("BENCH_HANG_INJECT: child should have been killed")
    t_birth = time.perf_counter()

    def _phase(name: str) -> None:
        # flushed per-phase breadcrumbs (child stdout): when an outer timeout
        # kills this child (the r4 batch-512 DNF was never diagnosed because
        # the child died silently), the captured partial output pinpoints
        # which phase — trace, XLA compile, or execute — ate the window. The
        # parent's robust_measure only reads the LAST stdout line of an
        # rc==0 child, so these extra lines never contaminate the result.
        _emit({
            "error": f"in progress; killed during child phase {name!r}",
            "event": "child_phase",
            "phase": name,
            "elapsed_s": round(time.perf_counter() - t_birth, 1),
        })

    _phase("import_jax")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mgproto_tpu.engine.train import Trainer

    _phase("init_model")
    cfg = flagship_config(fused, remat_stages, compute_dtype=compute_dtype)
    trainer = Trainer(cfg, steps_per_epoch=100, donate=True)
    state = trainer.init_state(jax.random.PRNGKey(0))

    host = np.random.RandomState(0)
    images = jnp.asarray(
        host.rand(BATCH, cfg.model.img_size, cfg.model.img_size, 3),
        jnp.float32,
    )

    if eval_mode:
        # inference reads only params/batch_stats/gmm — the steady-state
        # memory fill below is train-path-only and deliberately skipped
        t_c0 = time.perf_counter()
        eval_compiled = trainer._eval_step.lower(state, images, None).compile()
        eval_compile_s = time.perf_counter() - t_c0
        eval_flops = flops_from_cost_analysis(eval_compiled)

        def eval_step():
            return eval_compiled(state, images, None)

        out = None
        for _ in range(max(WARMUP, 1)):
            out = eval_step()
        # sync via host readback — same load-bearing caveat as the train
        # loop's sync point below (tunneled platforms + block_until_ready)
        float(jax.device_get(out.log_px[0]))
        t0 = time.perf_counter()
        for _ in range(ITERS):
            out = eval_step()
        float(jax.device_get(out.log_px[0]))
        dt = time.perf_counter() - t0
        return {
            # "mode" disambiguates this line from a train-step number when it
            # is read out of file context (ADVICE r4: the two were
            # shape-identical and only distinguishable by which file wrapped
            # them)
            "mode": "eval",
            "imgs_per_sec": BATCH * ITERS / dt,
            "step_time_s": dt / ITERS,
            "compile_s": round(eval_compile_s, 2),
            "flops_per_step": eval_flops,
            "device_kind": jax.devices()[0].device_kind,
            "batch": BATCH,
        }

    # steady state: all class queues full + touched, so EM is fully active
    mem = state.memory
    rng = jax.random.PRNGKey(1)
    feats = jax.random.uniform(rng, mem.feats.shape, jnp.float32)
    feats = feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)
    state = state.replace(
        memory=mem._replace(
            feats=feats,
            length=jnp.full_like(mem.length, mem.capacity),
            cursor=jnp.zeros_like(mem.cursor),
            updated=jnp.ones_like(mem.updated),
        )
    )

    labels = jnp.asarray(
        host.randint(0, cfg.model.num_classes, size=(BATCH,)), jnp.int32
    )

    # ONE compile, used for both the timed loop and the MFU cost analysis
    # (AOT executables are not inserted into the jit dispatch cache, so mixing
    # lower().compile() with trainer.train_step would compile twice).
    use_mine_arr = jnp.asarray(1.0, jnp.float32)
    update_gmm_arr = jnp.asarray(True, bool)
    # augmentation seeds operand (u8 wire format, ops/augment.py): the
    # bench feeds f32 images with device_augment off, so the stream is an
    # inert zero array
    seeds = jnp.zeros((BATCH,), jnp.uint32)
    _phase("trace_lower")
    lowered = trainer._train_step.lower(
        state, images, labels, seeds, use_mine_arr, update_gmm_arr, warm=False
    )
    _phase("xla_compile")
    t_c0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t_c0

    flops = flops_from_cost_analysis(compiled)  # best-effort: some PJRT
    # plugins return no cost model; MFU is then simply omitted

    def step(s):
        s, m = compiled(s, images, labels, seeds, use_mine_arr, update_gmm_arr)
        # keep EM active every iteration (enqueue alone re-marks only the
        # label classes)
        return s.replace(
            memory=s.memory._replace(updated=jnp.ones_like(s.memory.updated))
        ), m

    # NB: a host readback (device_get of a scalar) is the sync point; under
    # tunneled device platforms block_until_ready can return before the device
    # actually finishes, which inflates throughput ~1000x.
    metrics = None
    _phase("warmup_execute")
    for _ in range(max(WARMUP, 1)):  # >=1: the sync below needs a metrics
        state, metrics = step(state)
    float(jax.device_get(metrics.loss))

    # per-path telemetry (fresh registry: a child measures exactly one path).
    # The timed loop runs the AOT executable, which by construction cannot
    # retrace — so the watched jit handles' caches MUST stay empty, and
    # `recompile_count` is an invariant check, not a live retrace monitor: a
    # nonzero value means something dispatched the jit path mid-bench (i.e.
    # the measurement no longer times only the compiled step). The expected
    # compilation is the one explicit `lowered.compile()` above, reported as
    # `compile_count`/`compile_s`. Live shape-driven recompile telemetry
    # belongs to training runs (cli.train + StepMonitor).
    from mgproto_tpu.telemetry import MetricRegistry, StepMonitor
    from mgproto_tpu.telemetry.registry import percentile_from_buckets

    reg = MetricRegistry()
    mon = StepMonitor(registry=reg, phase="bench")
    mon.watch(lambda: trainer.jit_handles)
    mon.check_recompiles()  # baseline after warmup
    mon.record_cost_analysis(compiled)

    _phase("timed_loop")
    profile_dir = os.environ.get("BENCH_PROFILE_DIR")
    if profile_dir:
        # wrap ONLY the timed loop: the trace then contains exactly ITERS
        # steady-state steps — the artifact the MFU-headroom analysis reads
        jax.profiler.start_trace(profile_dir)
    t0 = time.perf_counter()
    prev = t0
    for _ in range(ITERS):
        state, metrics = step(state)
        now = time.perf_counter()
        # dispatch-interval per step; the final device sync below lands in
        # the headline dt only, so the histogram slightly undercounts the
        # last step — the percentiles are still the right shape signal
        mon.observe_step(BATCH, now - prev, check_recompiles=False)
        prev = now
    float(jax.device_get(metrics.loss))
    int(jax.device_get(state.step))
    dt = time.perf_counter() - t0
    if profile_dir:
        jax.profiler.stop_trace()
    mon.check_recompiles()
    hist = reg.histogram("step_time_seconds").snapshot_series(phase="bench")
    telemetry = {
        "step_time_hist": {
            "count": hist["count"],
            "mean_s": hist["sum"] / max(hist["count"], 1),
            "p50_s": percentile_from_buckets(hist, 50),
            "p90_s": percentile_from_buckets(hist, 90),
            "min_s": hist["min"],
            "max_s": hist["max"],
        },
        # the one AOT compile of the measured step (wall time: compile_s)
        "compile_count": 1,
        # invariant check (see comment above): 0 = the timed loop ran ONLY
        # the AOT executable; nonzero = a stray jit dispatch contaminated
        # the measurement
        "stray_jit_recompiles": mon.recompile_count,
    }
    # the HBM planner's predicted peak for THE program just timed, via
    # THE planner's own peak model on the same compiled module (ISSUE 14
    # satellite: the batch-512 resolution line records prediction NEXT TO
    # measurement, so a planner drift is a diff in the committed BENCH
    # artifact, not a belief)
    predicted_peak = None
    try:
        from mgproto_tpu.perf.planner import _program_peak

        predicted_peak, _ = _program_peak(compiled)
    except Exception:
        pass  # best-effort: some PJRT plugins expose no memory analysis
    return {
        "mode": "train",
        "imgs_per_sec": BATCH * ITERS / dt,
        "step_time_s": dt / ITERS,
        "compile_s": round(compile_s, 2),
        "flops_per_step": flops,
        "planner_predicted_peak_bytes": predicted_peak,
        "device_kind": jax.devices()[0].device_kind,
        "batch": BATCH,
        "compute_dtype": compute_dtype,
        "telemetry": telemetry,
    }


def robust_measure(name: str, measure: str, batch: int, reemit=None) -> tuple:
    """(result dict or None, last error string or None, attempts used).

    Retries with exponential backoff on ANY failure — the observed transients
    (backend-init refusal, mid-run `remote_compile: Connection refused`
    surfacing as JaxRuntimeError) are not reliably distinguishable from the
    error type alone, and a false-positive retry only costs time. Each attempt
    is a fresh child process (see the note by ATTEMPT_TIMEOUT_S), and each
    failed attempt flushes a JSON diagnostic line so an outer kill at any
    moment leaves a parseable last line. `reemit` (when set) re-flushes the
    caller's best-known partial RESULT line right after every in-progress
    emission, so once one scoring path has produced a number, the last line
    stays a number through the other path's attempts."""
    last_err = None
    cmd = [
        sys.executable, "-u", os.path.abspath(__file__),
        "--measure", measure, str(batch),
    ]
    # the optional best-batch entry is a bonus measurement: give a likely-
    # deterministic failure (e.g. HBM OOM at the bigger batch on a smaller
    # device) at most 2 attempts instead of burning the rare relay window
    # the reference-batch paths already used productively
    max_attempts = MAX_ATTEMPTS if name in ("unfused", "fused") else 2
    for attempt in range(1, max_attempts + 1):
        # enforce the whole-run cap BEFORE spending, and never hand a child
        # more than the remaining budget — otherwise a wedged relay overruns
        # DEADLINE_S by up to ATTEMPT_TIMEOUT_S per scoring path
        remaining = DEADLINE_S - (time.monotonic() - _START)
        if remaining <= 0:
            last_err = (last_err or "") + " [deadline exceeded, not attempted]"
            return None, last_err.strip(), attempt - 1
        _emit({
            # emitted BEFORE the child starts so a kill mid-attempt leaves a
            # last line that says exactly where the run died
            "error": f"in progress; killed during {name} attempt {attempt}",
            "event": "attempt_start",
            "path": name,
            "attempt": attempt,
            "budget_s": round(min(ATTEMPT_TIMEOUT_S, remaining), 1),
            "elapsed_s": round(time.monotonic() - _START, 1),
        })
        if reemit:
            reemit()
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=min(ATTEMPT_TIMEOUT_S, remaining),
            )
            if proc.returncode == 0 and proc.stdout.strip():
                return (
                    json.loads(proc.stdout.strip().splitlines()[-1]),
                    None,
                    attempt,
                )
            tail = (proc.stderr or proc.stdout or "").strip()[-600:]
            last_err = f"child rc={proc.returncode}: {tail}"
        except subprocess.TimeoutExpired as e:
            cause = (
                "whole-run deadline capped the attempt"
                if e.timeout < ATTEMPT_TIMEOUT_S
                else "relay hang?"
            )
            last_err = f"attempt killed after {e.timeout:.0f}s ({cause})"
        except Exception as e:
            last_err = f"{type(e).__name__}: {e}"
        print(f"[bench] attempt {attempt} failed: {last_err}", file=sys.stderr)
        _emit({
            "error": f"in progress; {name} attempt {attempt} failed",
            "event": "attempt_failed",
            "path": name,
            "attempt": attempt,
            "detail": last_err,
            "elapsed_s": round(time.monotonic() - _START, 1),
        })
        if reemit:
            reemit()
        if time.monotonic() - _START > DEADLINE_S:
            last_err += " [deadline exceeded, no more retries]"
            return None, last_err, attempt
        if attempt < max_attempts:
            time.sleep(BACKOFF_S[min(attempt - 1, len(BACKOFF_S) - 1)])
    return None, last_err, max_attempts


def _summary(results: dict, errors: dict, attempts_total: int,
             partial: bool) -> dict:
    """The driver-contract result line, shared by the partial emission (first
    path done) and the final one so the two can never drift in shape.

    The headline value/vs_baseline/mfu stay pinned to the REFERENCE-batch
    head-to-head (unfused/fused at batch 80) so rounds remain comparable and
    vs_baseline stays apples-to-apples with the batch-80 A100 estimate; the
    throughput-optimal batch entry is reported via its own keys only
    (fused_b<N>_imgs_per_sec, best_batch*)."""
    reference = {k: v for k, v in results.items()
                 if k in ("unfused", "fused")}
    # if BOTH reference-batch paths failed but a bonus measurement (e.g.
    # fused_b256) succeeded, fall back to it so the line still carries a real
    # number — but flag it: vs_baseline is then NOT apples-to-apples with the
    # batch-80 A100 estimate (ADVICE r4: winner_batch alone was easy to miss)
    headline_degraded = not reference
    reference = reference or results
    winner = max(reference, key=lambda k: reference[k]["imgs_per_sec"])
    best = results[winner]
    value = best["imgs_per_sec"]
    flops = best["flops_per_step"]
    peak = peak_flops(best["device_kind"])
    mfu = (flops / best["step_time_s"] / peak) if flops else None

    out = {
        "metric": "mgproto_r34_cub_train_step_throughput",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / A100_EST_IMAGES_PER_SEC, 3),
        "winner": winner,
        "winner_batch": best.get("batch"),
        "unfused_imgs_per_sec": round(
            results.get("unfused", {}).get("imgs_per_sec", 0.0), 2
        ),
        "fused_imgs_per_sec": round(
            results.get("fused", {}).get("imgs_per_sec", 0.0), 2
        ),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_per_step": flops,
        "device_kind": best["device_kind"],
        "north_star_frac_per_chip": round(value / NORTH_STAR_PER_CHIP, 3),
        "attempts": attempts_total,
    }
    if headline_degraded:
        out["headline_degraded"] = True
    if best.get("telemetry"):
        # winner's step-time histogram + recompile count: the BENCH_*.json
        # trajectory then carries its own dispersion/compile-health evidence
        out["telemetry"] = best["telemetry"]
    for name, r in results.items():
        if name not in ("unfused", "fused"):
            out[f"{name}_imgs_per_sec"] = round(r["imgs_per_sec"], 2)
            if r["imgs_per_sec"] > best["imgs_per_sec"]:
                out["best_batch"] = r.get("batch")
                out["best_batch_imgs_per_sec"] = round(r["imgs_per_sec"], 2)
                peak_b = peak_flops(r["device_kind"])
                out["best_batch_mfu"] = (
                    round(r["flops_per_step"] / r["step_time_s"] / peak_b, 4)
                    if r["flops_per_step"] else None
                )
    if partial:
        out["partial"] = True
    if errors:
        out["errors"] = errors
    return out


# Watcher-captured artifacts that may hold a real on-hardware measurement
# from an earlier relay window (written by scripts/tpu_window.sh stage 1).
# The newest parseable result line across them wins. Env-overridable
# (colon-separated; empty string disables) so the failure-contract tests can
# exercise the no-cache path from a repo that does contain the artifact.
_raw_cached = os.environ.get("BENCH_CACHED_SOURCES")
CACHED_SOURCES = tuple(
    s for s in (
        _raw_cached.split(":") if _raw_cached is not None
        else ["BENCH_PROBE_RUN.json"]
    ) if s
)
_BENCH_DIR = os.path.dirname(os.path.abspath(__file__))


def _cached_result() -> dict | None:
    """Most recent watcher-captured on-hardware result, or None.

    VERDICT r4 item 1: for four rounds the driver-window artifact came up
    empty whenever the relay happened to be down at driver time, while the
    SAME round's real measurement sat in BENCH_PROBE_RUN.json captured hours
    earlier by the window watcher. When the live probe gate fails, bench now
    emits that measurement as the final line — explicitly labeled, so cached
    is never presentable as live:

      {"cached": true, "measured_at": ..., "source": ..., ...result keys}

    The live attempt always comes first (probe diagnostics precede this), and
    a cached line is only emitted when no live number could be produced."""
    best = None
    for path in CACHED_SOURCES:
        full = os.path.join(_BENCH_DIR, path)
        try:
            with open(full) as f:
                lines = f.read().strip().splitlines()
        except OSError:
            continue
        measured_at = None
        result = None
        for line in lines:
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("event") == "start" and obj.get("ts"):
                measured_at = obj["ts"]
            if obj.get("unit") and obj.get("value") is not None:
                result = obj  # last full/partial result line wins
        if result is None:
            continue
        if measured_at is None:
            measured_at = time.strftime(
                "%Y-%m-%dT%H:%M:%S%z",
                time.localtime(os.path.getmtime(full)),
            )
        cand = dict(result)
        cand.update(cached=True, measured_at=measured_at, source=path)
        if best is None or _ts_key(cand["measured_at"]) > _ts_key(
                best["measured_at"]):
            best = cand
    return best


def _ts_key(ts) -> tuple:
    """Epoch-based sort key for an ISO-8601 %z timestamp; string fallback.
    Plain string comparison mis-orders stamps with different UTC offsets
    (the mtime fallback stamps local time) — normalize to epoch first."""
    try:
        import calendar
        st = time.strptime(str(ts), "%Y-%m-%dT%H:%M:%S%z")
        return (0, calendar.timegm(st) - (st.tm_gmtoff or 0), "")
    except (ValueError, TypeError):
        # unparseable stamps sort BEFORE any parsed one (they lose),
        # comparing among themselves as strings
        return (-1, 0, str(ts))


def _cached_age_s(cached: dict) -> float:
    """Age of a cached result in seconds; +inf for unparseable stamps (an
    unknown age must count as stale, not as fresh)."""
    kind, epoch, _ = _ts_key(cached.get("measured_at"))
    if kind != 0:
        return float("inf")
    return max(0.0, time.time() - epoch)


def measure_em() -> dict:
    """Hermetic EM-phase microbench: XLA cost analysis (FLOPs + bytes
    accessed) of one `em_update` call, old vs new path, at flagship shapes
    (C=200 classes, N=800 capacity, d=64, K=10, dirty width = batch 80 — the
    PERF.md steady state). CPU backend, no device timing, no relay: the
    delta is verifiable anywhere (`python bench.py --measure em`).

    The two compiled programs:
      * dense:        the pre-fast-path default (`max_active_classes=0`,
                      XLA e-step) — reduces over all C banks per EM round;
      * compact_fused: the compact dirty-class slab + fused E-step kernel
                      (interpret mode off-TPU), compiled WITHOUT the runtime
                      lax.cond dispatcher — cost analysis sums both branches
                      of a conditional, which would double-count the dense
                      fallback that steady state never executes.
    """
    import jax
    import jax.numpy as jnp

    from mgproto_tpu.config import EMConfig
    from mgproto_tpu.core import em as em_mod
    from mgproto_tpu.core.memory import init_memory
    from mgproto_tpu.core.mgproto import GMMState

    c, n, d, k = 200, 800, 64, 10
    width = _env_int("BENCH_EM_WIDTH", 80)  # = flagship batch 80

    key = jax.random.PRNGKey(0)
    feats = jax.random.uniform(key, (c, n, d), jnp.float32)
    feats = feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)
    mem = init_memory(c, n, d)._replace(
        feats=feats,
        length=jnp.full((c,), n, jnp.int32),
        # steady state: `width` classes dirty (one batch's worth)
        updated=jnp.arange(c) < width,
    )
    gmm = GMMState(
        means=jax.random.normal(jax.random.PRNGKey(1), (c, k, d)) * 0.1,
        sigmas=jnp.full((c, k, d), 1.0 / (2.0 * 3.14159265) ** 0.5),
        priors=jnp.full((c, k), 1.0 / k),
        keep=jnp.ones((c, k), bool),
    )

    def cost_of(fn, *args) -> dict:
        t0 = time.perf_counter()
        # donate like the production step does (engine/train.py donate=True):
        # without donation the unchanged [C, N, d] bank is copied through to
        # the output, charging both paths identical phantom traffic
        compiled = (
            jax.jit(fn, donate_argnums=(0, 1, 2)).lower(*args).compile()
        )
        compile_s = time.perf_counter() - t0
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        return {
            "flops": ca.get("flops"),
            "bytes_accessed": ca.get("bytes accessed", ca.get("bytes_accessed")),
            "compile_s": round(compile_s, 2),
        }

    dense_cfg = EMConfig(max_active_classes=0, fused_estep=False)
    compact_cfg = EMConfig(max_active_classes=width, fused_estep=True)
    dense_tx = em_mod.make_mean_optimizer(dense_cfg)
    opt = dense_tx.init(gmm.means)

    dense = cost_of(
        lambda g, m, o: em_mod.em_update(g, m, o, dense_tx, dense_cfg),
        gmm, mem, opt,
    )
    # private on purpose: the public em_update wraps this in the lax.cond
    # whose cost analysis would double-count (docstring above)
    fused, interpret = em_mod._resolve_fused_estep(compact_cfg)
    compact = cost_of(
        lambda g, m, o: em_mod._compact_em_update(
            g, m, o, dense_tx, compact_cfg, 1e-10, width, fused, interpret
        ),
        gmm, mem, opt,
    )

    def ratio(a, b):
        if not a or not b:
            return None
        return round(a / b, 3)

    return {
        "metric": "em_update_cost_analysis",
        "backend": jax.default_backend(),
        "shapes": {"C": c, "N": n, "d": d, "K": k, "width": width},
        "dense": dense,
        "compact_fused": compact,
        "flops_ratio_dense_over_compact": ratio(
            dense["flops"], compact["flops"]
        ),
        "bytes_ratio_dense_over_compact": ratio(
            dense["bytes_accessed"], compact["bytes_accessed"]
        ),
    }


def measure_overlap() -> dict:
    """Hermetic trunk/bank-split microbench: XLA cost + memory analysis of
    the monolithic train step vs the async pipeline's trunk and bank
    programs (`python bench.py --measure overlap`, CPU backend, compile
    only — no device timing, no relay).

    What it demonstrates (the ISSUE-6 acceptance evidence, recorded in
    evidence/overlap_bench.json):

      * CRITICAL PATH: the trunk program accesses strictly fewer bytes than
        the monolithic step — the bank phase's traffic (the [C, cap, d]
        gather/update + EM reductions) left the program whose latency every
        step serializes on, which is exactly what the async pipeline hides
        behind the next trunk;
      * DONATION: the bank program compiled WITH bank-buffer donation has a
        lower peak (arguments+outputs+temps-aliasing) than the same program
        without — the bank is updated in place instead of existing twice.

    Shapes are tiny-trunk + mid-sized-bank (the split moves BANK bytes, so
    the bank dominates on purpose); env-tunable like --measure em.
    """
    import jax
    import jax.numpy as jnp

    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.perf.planner import _program_peak, lower_split_programs

    c = _env_int("BENCH_OVERLAP_CLASSES", 64)
    cap = _env_int("BENCH_OVERLAP_CAP", 256)
    d = _env_int("BENCH_OVERLAP_DIM", 64)
    batch = _env_int("BENCH_OVERLAP_BATCH", 32)

    import dataclasses

    base = tiny_test_config(
        num_classes=c, mem_capacity=cap, proto_dim=d, prototypes_per_class=4
    )

    def steady_state(trainer):
        state = trainer.init_state(jax.random.PRNGKey(0))
        mem = state.memory
        feats = jax.random.uniform(jax.random.PRNGKey(1), mem.feats.shape)
        feats = feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)
        return state.replace(memory=mem._replace(
            feats=feats,
            length=jnp.full_like(mem.length, mem.capacity),
            updated=jnp.ones_like(mem.updated),
        ))

    def cost_of(compiled, t0) -> dict:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        peak, _ = _program_peak(compiled)
        return {
            "flops": ca.get("flops"),
            "bytes_accessed": ca.get(
                "bytes accessed", ca.get("bytes_accessed")
            ),
            "peak_bytes": peak,
            "compile_s": round(time.perf_counter() - t0, 2),
        }

    images = jnp.zeros((batch, base.model.img_size, base.model.img_size, 3),
                       jnp.float32)
    labels = jnp.zeros((batch,), jnp.int32)
    seeds = jnp.zeros((batch,), jnp.uint32)
    use_mine = jnp.asarray(1.0, jnp.float32)
    update_gmm = jnp.asarray(True, bool)

    # monolithic (sync) step, donated like production
    sync_tr = Trainer(
        base.replace(em=dataclasses.replace(base.em, async_bank=False)),
        steps_per_epoch=100, donate=True,
    )
    state = steady_state(sync_tr)
    t0 = time.perf_counter()
    monolithic = cost_of(
        sync_tr._train_step.lower(
            state, images, labels, seeds, use_mine, update_gmm, warm=False
        ).compile(),
        t0,
    )

    # the pipelined programs — lowered by the SAME helper the planner's
    # measure_candidate uses, so this bench and --auto_tune can never
    # measure different programs
    async_tr = Trainer(
        base.replace(em=dataclasses.replace(base.em, async_bank=True)),
        steps_per_epoch=100, donate=True,
    )
    state_a = steady_state(async_tr)
    trunk_lowered, bank_lowered = lower_split_programs(
        async_tr, state_a, images, labels, seeds, use_mine, update_gmm
    )
    t0 = time.perf_counter()
    trunk = cost_of(trunk_lowered.compile(), t0)
    t0 = time.perf_counter()
    bank_donated = cost_of(bank_lowered.compile(), t0)
    # the undonated comparison point: the identical bank program without
    # the in-place alias — its peak difference IS the donation saving
    undonated_tr = Trainer(
        base.replace(em=dataclasses.replace(base.em, async_bank=True)),
        steps_per_epoch=100, donate=False,
    )
    state_u = steady_state(undonated_tr)
    _, bank_undonated_lowered = lower_split_programs(
        undonated_tr, state_u, images, labels, seeds, use_mine, update_gmm
    )
    t0 = time.perf_counter()
    bank_undonated = cost_of(bank_undonated_lowered.compile(), t0)

    def ratio(a, b):
        if not a or not b:
            return None
        return round(a / b, 3)

    return {
        "metric": "trunk_bank_overlap_cost_analysis",
        "backend": jax.default_backend(),
        "shapes": {"C": c, "cap": cap, "d": d, "batch": batch},
        "monolithic": monolithic,
        "trunk": trunk,
        "bank_donated": bank_donated,
        "bank_undonated": bank_undonated,
        # the bank phase's bytes, now OFF the step's serialized path
        "trunk_bytes_removed_from_critical_path": (
            (monolithic["bytes_accessed"] - trunk["bytes_accessed"])
            if monolithic["bytes_accessed"] and trunk["bytes_accessed"]
            else None
        ),
        "bytes_ratio_monolithic_over_trunk": ratio(
            monolithic["bytes_accessed"], trunk["bytes_accessed"]
        ),
        "bank_peak_ratio_undonated_over_donated": ratio(
            bank_undonated["peak_bytes"], bank_donated["peak_bytes"]
        ),
    }


def measure_dtype() -> dict:
    """Hermetic mixed-precision microbench (`python bench.py --measure
    dtype`, CPU-friendly): the flagship step compiled/lowered at f32 AND
    bf16, reporting both byte views per dtype —

      * `cost_*`: XLA's compiled-module cost/memory analysis via the
        shared `obs.stall.step_costs` -> `lower_step_programs` helper
        (the planner's own machinery). CAVEAT, in-band: on CPU, float
        normalization rewrites bf16 programs into f32-with-converts, so
        these columns under-report the dtype win off-TPU;
      * `model_*`: the dtype-aware StableHLO byte model
        (`obs.stall.step_byte_model`) — logical dtypes, backend-neutral
        shapes. The headline `bytes_ratio_f32_over_bf16` comes from its
        ideal-fusion total: the number the acceptance gate and the
        committed evidence/dtype_bench.json carry.

    Env knobs: BENCH_DTYPE_BATCH (default 256 — the flagship operating
    point; shrink for smoke runs), BENCH_DTYPE_NO_COMPILE=1 skips the
    slow compile half (model columns only), BENCH_DTYPE_TINY=1 swaps the
    flagship for the tiny test config (harness smoke in seconds — the
    committed artifact is always the flagship)."""
    if os.environ.get("BENCH_FAIL_INJECT"):
        # deterministic failure for the cached-fallback contract tests
        # (same knob as run_config): fires before any jax work
        raise RuntimeError("BENCH_FAIL_INJECT: simulated dtype failure")
    import dataclasses

    from mgproto_tpu.obs import stall

    tiny = bool(os.environ.get("BENCH_DTYPE_TINY"))
    batch = _env_int("BENCH_DTYPE_BATCH", 256)
    do_compile = not os.environ.get("BENCH_DTYPE_NO_COMPILE")
    out: dict = {
        "metric": "dtype_bytes_model",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "batch": batch,
        "backend": None,
        "config": "tiny" if tiny else "flagship",
        "compiled_costs": bool(do_compile),
    }
    for name, dt in (("f32", "float32"), ("bf16", "bfloat16")):
        if tiny:
            from mgproto_tpu.config import tiny_test_config

            base = tiny_test_config()
            cfg = base.replace(
                model=dataclasses.replace(base.model, compute_dtype=dt)
            )
        else:
            cfg = flagship_config(fused=False, compute_dtype=dt)
        # one trace/lowering per dtype, shared by the model walk and the
        # compiled cost analysis
        lowered = stall.lower_step_programs(cfg, batch)
        model = stall.step_byte_model(
            cfg, batch=batch, top_n=6 if dt == "bfloat16" else 0,
            lowered=lowered,
        )
        out["backend"] = model["backend"]
        entry = {
            "model_raw_bytes": model["raw_bytes"],
            "model_fused_bytes": model["fused_bytes"],
        }
        if dt == "bfloat16":
            out["top_byte_movers"] = model["top_byte_movers"]
        if do_compile:
            costs = stall.step_costs(cfg, batch=batch, lowered=lowered)
            entry.update({
                "cost_bytes_accessed": costs["bytes_accessed"],
                "cost_peak_bytes": costs["peak_bytes"],
                "flops": costs["flops"],
            })
        out[name] = entry

    def ratio(a, b):
        if not a or not b:
            return None
        return round(a / b, 3)

    out["bytes_ratio_f32_over_bf16"] = ratio(
        out["f32"]["model_fused_bytes"], out["bf16"]["model_fused_bytes"]
    )
    out["raw_bytes_ratio_f32_over_bf16"] = ratio(
        out["f32"]["model_raw_bytes"], out["bf16"]["model_raw_bytes"]
    )
    if do_compile:
        out["cost_bytes_ratio_f32_over_bf16"] = ratio(
            out["f32"]["cost_bytes_accessed"],
            out["bf16"]["cost_bytes_accessed"],
        )
        out["peak_ratio_f32_over_bf16"] = ratio(
            out["f32"]["cost_peak_bytes"], out["bf16"]["cost_peak_bytes"]
        )
    return out


def _measure_with_cached_fallback(measure_fn, evidence_name: str) -> None:
    """The ONE cached-fallback/staleness wrapper hermetic measures share
    (`--measure dtype` / `--measure coldstart`): emit the live result and
    exit 0, or — on ANY failure (the CPU compile half can die on a wedged
    machine, and on-TPU invocations ride the same flaky relay as
    everything else) — re-emit the committed evidence/<name> as the final
    line, explicitly `cached: true`, stamped with the live error as
    `probe_failure` and its age (stale beyond BENCH_CACHED_MAX_AGE_S
    exits 1), so a flaky window degrades DIAGNOSABLY instead of
    flatlining the trajectory."""
    try:
        print(json.dumps(measure_fn()), flush=True)
        raise SystemExit(0)
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — every failure must degrade
        failure = {"error": f"{type(e).__name__}: {e}"}
    cached_path = os.path.join(_BENCH_DIR, "evidence", evidence_name)
    try:
        with open(cached_path) as f:
            cached = json.loads(f.read().strip().splitlines()[-1])
    except (OSError, ValueError, IndexError):
        _emit({"error": f"measure failed and no cached "
                        f"evidence/{evidence_name} exists",
               "probe_failure": failure})
        raise SystemExit(1)
    cached["cached"] = True
    cached["probe_failure"] = failure
    cached["measured_at"] = cached.get("ts")
    age = _cached_age_s(cached)
    cached["cached_age_s"] = None if age == float("inf") else round(age, 1)
    if age > CACHED_MAX_AGE_S:
        cached["stale"] = True
        _emit(cached)
        raise SystemExit(1)
    _emit(cached)
    raise SystemExit(0)


def measure_coldstart() -> dict:
    """Hermetic cold-vs-warm replica-start microbench (`python bench.py
    --measure coldstart`, CPU-friendly): the ISSUE-13 AOT executable
    cache's before/after. Two ServingEngines over the same tiny state and
    a fresh ExecutableCache:

      * COLD  — empty cache: every bucket misses, compiles, and is
        lazily stored (compile-everything warmup, the pre-cache world,
        plus the one-time serialization cost);
      * WARM  — same cache: every bucket deserializes (the mmap-and-go
        replica start a scale-up or blue/green swap pays).

    Per-bucket breakdown from `ServingEngine.warmup_report`, one JSON
    line; the committed artifact is evidence/coldstart_bench.json (schema
    in evidence/README.md). The WARM engine must perform ZERO XLA
    compiles — asserted here through the StepMonitor-backed warmup return,
    not just reported.

    Env knobs: BENCH_COLDSTART_BUCKETS (default "1,2,4,8")."""
    if os.environ.get("BENCH_FAIL_INJECT"):
        # deterministic failure for the cached-fallback contract tests
        raise RuntimeError("BENCH_FAIL_INJECT: simulated coldstart failure")
    import shutil
    import tempfile

    import jax

    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.serving import metrics as sm
    from mgproto_tpu.serving.aotcache import ExecutableCache
    from mgproto_tpu.serving.engine import ServingEngine
    from mgproto_tpu.telemetry.registry import (
        MetricRegistry,
        set_current_registry,
    )

    buckets = tuple(
        int(b)
        for b in os.environ.get("BENCH_COLDSTART_BUCKETS", "1,2,4,8")
        .split(",") if b.strip()
    )
    registry = MetricRegistry()
    prev = set_current_registry(registry)
    cache_dir = tempfile.mkdtemp(prefix="mgproto_cold_")
    try:
        sm.register_serving_metrics(registry)
        cfg = tiny_test_config()
        trainer = Trainer(cfg, steps_per_epoch=1)
        state = trainer.init_state(jax.random.PRNGKey(0))
        cache = ExecutableCache(cache_dir)

        def run(label):
            engine = ServingEngine.from_live(
                trainer, state, buckets=buckets, aot_cache=cache
            )
            t0 = time.perf_counter()
            compiles = engine.warmup()
            total = time.perf_counter() - t0
            return {
                "total_s": round(total, 6),
                "compiles": compiles,
                "per_bucket": [
                    {**row, "seconds": round(row["seconds"], 6)}
                    for row in engine.warmup_report
                ],
            }

        cold = run("cold")
        warm = run("warm")
        if warm["compiles"] != 0:
            raise RuntimeError(
                f"warm start compiled {warm['compiles']}x — the AOT cache "
                "was bypassed or every entry was rejected"
            )
        return {
            "metric": "coldstart",
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "backend": jax.default_backend(),
            "config": "tiny",
            "buckets": list(buckets),
            "cold": cold,
            "warm": warm,
            "speedup_cold_over_warm": (
                round(cold["total_s"] / warm["total_s"], 2)
                if warm["total_s"] > 0 else None
            ),
            "aot": {
                "hits": registry.counter(sm.AOT_HITS).value(),
                "misses": registry.counter(sm.AOT_MISSES).value(),
                "stores_ok": registry.counter(sm.AOT_STORES).value(
                    result="ok"
                ),
            },
        }
    finally:
        set_current_registry(prev)
        shutil.rmtree(cache_dir, ignore_errors=True)


def collective_bytes_from_hlo(hlo_text: str) -> dict:
    """Per-chip collective traffic of a compiled SPMD module, by op kind.

    Post-partitioning optimized HLO carries PER-DEVICE shapes, so summing
    each collective op's RESULT bytes gives bytes landing on one chip per
    step — the hermetic stand-in for the fleet observatory's
    `allgather_bytes_total / host_local_device_count` measure, derivable
    without running anything. Start/done async pairs are counted once: the
    `-start` op carries the payload (its `-done` is a token), and because
    an async start's TUPLE result also lists the ALIASED INPUT buffer
    element, a `-start` op counts only its LARGEST tuple element (the
    gathered output) — summing the tuple would bill input+output for one
    transfer. Sync multi-operand collectives (a tuple reduce-scatter of
    two tensors really does produce two results) keep the sum.

    Besides per-kind totals, the result splits the two scaling families
    the weak-scaling gate must treat differently: `gather_family` bytes
    (all-gather / reduce-scatter / all-to-all — per-chip bytes scale with
    the (N-1)/N gather fraction of a fixed payload) and
    `allreduce_family` bytes (all-reduce / collective-permute — per-chip
    result bytes are ~constant in N)."""
    import re

    dtype_bytes = {
        "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
        "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
        "pred": 1,
    }
    kinds = ("all-gather", "all-reduce", "reduce-scatter",
             "collective-permute", "all-to-all")
    out = {k: 0 for k in kinds}
    out["max_op"] = 0  # largest single collective result (bank-gather tell)
    # one instruction per line: `%name = <shape(s)> <op>(`; tuple-shaped
    # results list every element shape before the op name
    line_re = re.compile(
        r"=\s+(?P<shapes>[^=]*?)\s+(?P<op>" + "|".join(kinds)
        + r")(?P<start>-start)?\("
    )
    shape_re = re.compile(r"(?P<dt>[a-z]+\d*|pred)\[(?P<dims>[\d,]*)\]")
    for line in hlo_text.splitlines():
        m = line_re.search(line)
        if not m or f"{m.group('op')}-done" in line:
            continue
        elems = []
        for sm in shape_re.finditer(m.group("shapes")):
            dt = dtype_bytes.get(sm.group("dt"))
            if dt is None:
                continue
            n = 1
            for d in sm.group("dims").split(","):
                if d:
                    n *= int(d)
            elems.append(n * dt)
        if not elems:
            continue
        nbytes = max(elems) if m.group("start") else sum(elems)
        out[m.group("op")] += nbytes
        out["max_op"] = max(out["max_op"], nbytes)
    out["total"] = sum(out[k] for k in kinds)
    out["gather_family"] = (
        out["all-gather"] + out["reduce-scatter"] + out["all-to-all"]
    )
    out["allreduce_family"] = out["all-reduce"] + out["collective-permute"]
    return out


def _weakscale_config(chips: int, per_chip_batch: int):
    """The weak-scaling probe config: class axis sharded over ALL `chips`
    (mesh data=1, model=chips — the axis ISSUE 14 makes first-class), the
    global batch grown ~chips so per-chip rows stay constant (weak scaling),
    compact EM narrower than the per-shard class slab so the shard-local
    dirty-class gather is the compiled path."""
    import dataclasses

    from mgproto_tpu.config import MeshConfig, tiny_test_config

    cfg = tiny_test_config(
        num_classes=_env_int("BENCH_WEAKSCALE_CLASSES", 32),
        prototypes_per_class=2,
        proto_dim=32,
        img_size=32,
        # the bank must DOMINATE every other gatherable buffer (activation
        # row-gathers at the data->model boundary top out well below it at
        # these shapes), so the max-collective-op gate detects a leaked
        # bank gather instead of tripping on ordinary scoring traffic
        mem_capacity=_env_int("BENCH_WEAKSCALE_MEMCAP", 256),
        mine_T=4,
    )
    return cfg.replace(
        data=dataclasses.replace(
            cfg.data,
            train_batch_size=per_chip_batch * chips,
            device_augment=False,
        ),
        em=dataclasses.replace(
            cfg.em,
            async_bank=False,  # ONE program: attribution stays simple
            max_active_classes=_env_int("BENCH_WEAKSCALE_EM_WIDTH", 4),
        ),
        mesh=MeshConfig(data=1, model=chips),
    )


def measure_weakscale_probe(chips: int) -> dict:
    """One weak-scaling point, run in a CHILD whose XLA_FLAGS forced
    `chips` host-platform devices (the parent `measure_weakscale` sets the
    env — device count is fixed at backend init, so every point needs its
    own process). Hermetic compile-only measurement of the production
    ShardedTrainer step at mesh (data=1, model=chips):

      * per-chip BANK / OPTIMIZER / PARAM bytes — read from the LIVE
        sharded state's own shard shapes (ground truth), with the
        planner's shape-math prediction (perf/planner.state_bytes_per_chip
        — the same numbers the telemetry gauges carry) beside it;
      * per-chip collective traffic — summed from the compiled module's
        post-partitioning HLO (collective_bytes_from_hlo), so "EM never
        gathers another shard's bank" is a measured byte count, not a
        docstring;
      * per-chip flops / bytes-accessed from XLA cost analysis, folded
        through the v5e roofline (PEAK_BF16 + DEFAULT_HBM_BYTES_PER_S)
        into a modeled img/s/chip — the flat-within-tolerance curve
        `mgproto-telemetry check --weakscale` gates. Modeled, not timed:
        N virtual chips share one physical CPU, so wall time ~N would
        measure the sandbox, not the sharding.
    """
    import jax
    import numpy as np

    from mgproto_tpu.obs.stall import DEFAULT_HBM_BYTES_PER_S
    from mgproto_tpu.parallel import ShardedTrainer, make_mesh
    from mgproto_tpu.perf.planner import state_bytes_per_chip

    if jax.device_count() != chips:
        raise RuntimeError(
            f"probe expected {chips} devices, backend has "
            f"{jax.device_count()} — XLA_FLAGS not honored?"
        )
    per_chip_batch = _env_int("BENCH_WEAKSCALE_BATCH", 4)
    cfg = _weakscale_config(chips, per_chip_batch)
    trainer = ShardedTrainer(
        cfg, steps_per_epoch=10, mesh=make_mesh(data=1, model=chips)
    )
    state = trainer.prepare(trainer.init_state(jax.random.PRNGKey(0)))

    def shard_bytes(tree) -> int:
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            if not hasattr(leaf, "sharding"):
                continue
            shape = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shape)) * leaf.dtype.itemsize
        return int(total)

    b = cfg.data.train_batch_size
    images = jax.ShapeDtypeStruct(
        (b, cfg.model.img_size, cfg.model.img_size, 3), np.float32
    )
    labels = jax.ShapeDtypeStruct((b,), np.int32)
    compiled = trainer.lower_train_step(state, images, labels).compile()
    flops = flops_from_cost_analysis(compiled) or 0.0
    bytes_accessed = 0.0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        bytes_accessed = float((ca or {}).get("bytes accessed", 0.0))
    except Exception:
        pass
    collectives = collective_bytes_from_hlo(compiled.as_text())
    peak = PEAK_BF16["v5e"]
    modeled_step_s = max(
        flops / peak, bytes_accessed / DEFAULT_HBM_BYTES_PER_S
    ) or None
    return {
        "chips": chips,
        "global_batch": b,
        "per_chip_batch": per_chip_batch,
        "num_classes": cfg.model.num_classes,
        "classes_per_chip": cfg.model.num_classes // chips,
        # live shard-shape ground truth
        "bank_bytes_per_chip": shard_bytes(state.memory),
        "opt_bytes_per_chip": (
            shard_bytes(state.opt_state)
            + shard_bytes(state.warm_opt_state)
            + shard_bytes(state.proto_opt_state)
        ),
        "param_bytes_per_chip": shard_bytes(state.params),
        # the planner's shape-math prediction (telemetry gauge provenance)
        "planner": state_bytes_per_chip(cfg, chips, state=state),
        # compiled-module measures (per-device under SPMD partitioning).
        # The two scaling families are split because the flatness gate
        # must normalize them differently: gather-family per-chip bytes
        # follow S*(N-1)/N for a fixed payload S, all-reduce-family
        # per-chip result bytes are ~constant in N.
        "collective_bytes_per_chip_per_step": collectives,
        "gather_bytes_per_chip_per_step": collectives["gather_family"],
        "allreduce_bytes_per_chip_per_step": collectives[
            "allreduce_family"
        ],
        "flops_per_chip_per_step": flops,
        "bytes_accessed_per_chip_per_step": bytes_accessed,
        "modeled_step_s": modeled_step_s,
        "modeled_img_per_sec_per_chip": (
            per_chip_batch / modeled_step_s if modeled_step_s else None
        ),
    }


def measure_weakscale() -> dict:
    """Hermetic weak-scaling harness (`python bench.py --measure
    weakscale`, CPU-friendly — the ISSUE 14 deliverable): one probe child
    per chip count (XLA host-platform virtual devices, 1 -> 2 -> 4 -> 8 by
    default), one JSON record with the whole curve. Committed as
    evidence/weakscale_bench.json and gated by `mgproto-telemetry check
    --weakscale`, which RE-DERIVES every verdict from the raw entries:
    bank/optimizer bytes per chip must shrink ~1/model_axis (>=1.8x at
    model=2), collective bytes/chip and modeled img/s/chip must stay flat
    within tolerance. Env knobs: BENCH_WEAKSCALE_CHIPS (default
    "1,2,4,8"), BENCH_WEAKSCALE_BATCH / _CLASSES / _EM_WIDTH."""
    if os.environ.get("BENCH_FAIL_INJECT"):
        # deterministic failure for the cached-fallback contract tests
        raise RuntimeError("BENCH_FAIL_INJECT: simulated weakscale failure")
    import subprocess

    chips_list = [
        int(c)
        for c in os.environ.get("BENCH_WEAKSCALE_CHIPS", "1,2,4,8")
        .split(",") if c.strip()
    ]
    entries = []
    for chips in chips_list:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={chips}"
        )
        # the axon sitecustomize must not redirect the child to a TPU relay
        env["PALLAS_AXON_POOL_IPS"] = ""
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--measure", "weakscale_probe", str(chips)],
            capture_output=True, text=True, env=env, cwd=_BENCH_DIR,
            timeout=_env_int("BENCH_WEAKSCALE_TIMEOUT_S", 420),
        )
        lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
        if proc.returncode != 0 or not lines:
            raise RuntimeError(
                f"weakscale probe chips={chips} rc={proc.returncode}: "
                f"{(proc.stderr or proc.stdout)[-400:]}"
            )
        entries.append(json.loads(lines[-1]))
    by_chips = {e["chips"]: e for e in entries}
    summary = {}
    if 1 in by_chips and 2 in by_chips:
        summary["bank_reduction_at_2"] = round(
            by_chips[1]["bank_bytes_per_chip"]
            / max(by_chips[2]["bank_bytes_per_chip"], 1), 3
        )
        summary["opt_reduction_at_2"] = round(
            by_chips[1]["opt_bytes_per_chip"]
            / max(by_chips[2]["opt_bytes_per_chip"], 1), 3
        )
    return {
        "metric": "weakscale",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "backend": "cpu (xla_force_host_platform_device_count)",
        "mesh": "data=1, model=<chips> — the class-sharding axis",
        "config": {
            "per_chip_batch": _env_int("BENCH_WEAKSCALE_BATCH", 4),
            "num_classes": _env_int("BENCH_WEAKSCALE_CLASSES", 32),
            "em_width": _env_int("BENCH_WEAKSCALE_EM_WIDTH", 4),
        },
        "chips": chips_list,
        "entries": entries,
        "summary": summary,
    }


def measure_quant() -> dict:
    """Hermetic int8 weight-only serving harness (`python bench.py
    --measure quant`, CPU-friendly — the ISSUE 20 deliverable): ONE
    record carrying everything `mgproto-telemetry check --quant`
    re-derives, all measured through the PRODUCTION export + serving
    stack over the trust drill's seeded toy:

      * per-leaf weight-byte rows (f32 vs int8+scales) — the >=3x
        backbone reduction, re-summable;
      * int8 program vs its embedded dequantize-to-f32 debug twin:
        per-sample per-logit and log p(x) deltas (the parity pin);
      * the serve-bucket ladder: `plan_serve_buckets` with the explicit
        weight-resident term under ONE shared budget, f32 vs int8 — the
        int8 ladder must be strictly longer (modeled-latency/packing
        headroom the 4x weight shrink buys);
      * two full trust matrices (trust/matrix.py) — one per artifact,
        raw scores and outcome counts included, so OoD-AUROC and
        answered-accuracy deltas are re-derivable;
      * the quant-mismatch drill: an f32-stamped calibration grafted
        into a copy of the int8 artifact must trip
        serving_quant_mismatch_total, degrade the gate, and be rejected
        by `verify_head` with 'quant_mismatch' — fail-closed, OBSERVED.

    Env knobs: BENCH_QUANT_BUCKETS (default "1,2,4,8"),
    BENCH_QUANT_PER_CLASS (default 8), BENCH_QUANT_KINDS (default
    "noise,contrast"), BENCH_QUANT_SEVERITIES (default "1,3,5"),
    BENCH_QUANT_TOL (default 1e-3 — the parity pin)."""
    if os.environ.get("BENCH_FAIL_INJECT"):
        # deterministic failure for the cached-fallback contract tests
        raise RuntimeError("BENCH_FAIL_INJECT: simulated quant failure")
    import dataclasses as _dc
    import shutil
    import tempfile

    import jax
    import numpy as np

    from mgproto_tpu.cli.trust import _samples
    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.export import (
        artifact_meta,
        embed_calibration,
        export_eval,
        load_artifact,
        save_artifact,
    )
    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.online.capture import CapturedSample
    from mgproto_tpu.online.consolidate import (
        Consolidator,
        ConsolidatorConfig,
    )
    from mgproto_tpu.perf.planner import plan_serve_buckets
    from mgproto_tpu.perf.quant import quantize_params
    from mgproto_tpu.serving import metrics as sm
    from mgproto_tpu.serving.calibration import calibrate, gmm_fingerprint
    from mgproto_tpu.serving.engine import ServingEngine
    from mgproto_tpu.serving.swap import verify_head
    from mgproto_tpu.telemetry.registry import (
        MetricRegistry,
        set_current_registry,
    )
    from mgproto_tpu.trust.matrix import MatrixConfig, run_matrix

    buckets = tuple(
        int(b)
        for b in os.environ.get("BENCH_QUANT_BUCKETS", "1,2,4,8")
        .split(",") if b.strip()
    )
    per_class = _env_int("BENCH_QUANT_PER_CLASS", 8)
    kinds = tuple(
        k.strip()
        for k in os.environ.get("BENCH_QUANT_KINDS", "noise,contrast")
        .split(",") if k.strip()
    )
    severities = tuple(
        int(s)
        for s in os.environ.get("BENCH_QUANT_SEVERITIES", "1,3,5")
        .split(",") if s.strip()
    )
    tol = float(os.environ.get("BENCH_QUANT_TOL", "1e-3"))
    classes, seed = 4, 0

    registry = MetricRegistry()
    prev = set_current_registry(registry)
    tmp = tempfile.mkdtemp(prefix="mgproto_quant_")
    try:
        sm.register_serving_metrics(registry)
        # ---- bootstrap the trust drill's toy through the production
        # consolidation path (real served accuracy, not decorative)
        cfg = tiny_test_config(num_classes=classes)
        cfg = cfg.replace(em=_dc.replace(cfg.em, mean_lr=0.05))
        trainer = Trainer(cfg, steps_per_epoch=1)
        state = trainer.init_state(jax.random.PRNGKey(seed))
        img = cfg.model.img_size
        rng = np.random.RandomState(seed + 11)
        cons = Consolidator(
            trainer, state,
            config=ConsolidatorConfig(cadence_s=1.0, batch_width=8),
            clock=lambda: 0.0,
        )
        for _ in range(20):
            for c in range(classes):
                cons.ingest([
                    CapturedSample(p, c, None, "bootstrap", True)
                    for p in _samples(rng, c, img, 8)
                ])
        state = cons.candidate_state(state)

        # ---- quantize; the int8 program serves the ROUND-TRIPPED grid,
        # so its calibration is measured through those exact weights
        q = quantize_params(state.params)
        rt_state = state.replace(params=q.materialize(barrier=False))
        qc = q.quant_config()
        int8_w, f32_w = qc["total_weight_bytes"], qc["total_f32_bytes"]
        reduction = f32_w / max(int8_w, 1)
        if reduction < 3.0:
            raise RuntimeError(
                f"weight-bytes reduction {reduction:.2f}x < the 3x "
                "acceptance floor — quantization covered too little of "
                "the backbone"
            )

        calib_batches = [
            (_samples(rng, c, img, 8), np.full((8,), c, np.int32))
            for c in range(classes) for _ in range(2)
        ]
        calib_f32 = calibrate(trainer, state, calib_batches,
                              source="quant-bench f32")
        calib_int8 = calibrate(trainer, rt_state, calib_batches,
                               source="quant-bench int8",
                               quant_config=q.policy.tag)

        # ---- the two artifacts, through the production export path
        f32_path = os.path.join(tmp, "f32.mgproto")
        int8_path = os.path.join(tmp, "int8.mgproto")
        fp = gmm_fingerprint(state.gmm)
        save_artifact(
            f32_path, export_eval(trainer, state),
            artifact_meta(cfg, None, True, gmm_fingerprint=fp),
            calibration=calib_f32,
        )
        save_artifact(
            int8_path, export_eval(trainer, state, quantized=q),
            artifact_meta(cfg, None, True, gmm_fingerprint=fp, quant=qc),
            calibration=calib_int8,
            dequant=export_eval(trainer, rt_state),
        )

        # ---- parity: int8 program vs its dequantize-to-f32 debug twin
        id_parts, id_labels = [], []
        for c in range(classes):
            id_parts.append(_samples(rng, c, img, per_class))
            id_labels.append(np.full((per_class,), c, np.int32))
        id_images = np.concatenate(id_parts).astype(np.float32)
        id_labels = np.concatenate(id_labels)
        call8, _ = load_artifact(int8_path)
        calld, _ = load_artifact(int8_path, dequantize=True)
        out8 = jax.device_get(call8(id_images))
        outd = jax.device_get(calld(id_images))
        logit_delta = [
            float(d) for d in
            np.abs(out8["logits"] - outd["logits"]).max(axis=1)
        ]
        px_delta = [
            float(d) for d in np.abs(out8["log_px"] - outd["log_px"])
        ]
        parity = {
            "tolerance": tol,
            "logit_delta_max_per_sample": logit_delta,
            "log_px_delta": px_delta,
            "max_logit_delta": max(logit_delta),
            "max_log_px_delta": max(px_delta),
        }

        # ---- engines + trust matrices (drill-scale committed bars, the
        # run_synthetic_matrix convention: the MACHINERY is what's gated)
        mc = MatrixConfig(
            seed=seed, kinds=kinds, severities=severities,
            auroc_floor=0.85, answered_accuracy_floor=0.30,
            monotone_tol=0.05,
        )
        ood = {
            "inverted": np.concatenate([
                _samples(rng, c, img, per_class // 2, channel=-2.0)
                for c in range(classes)
            ]),
            "dimmed": np.concatenate([
                _samples(rng, c, img, per_class // 2, channel=0.0)
                for c in range(classes)
            ]),
        }
        trust = {}
        engines = {}
        for name, path in (("f32", f32_path), ("int8", int8_path)):
            engine = ServingEngine.from_artifact(path, buckets=buckets)
            engine.warmup()
            engines[name] = engine
            trust[name] = run_matrix(engine, id_images, id_labels, ood, mc)

        # ---- planner ladder under ONE shared budget: probe the int8
        # program peaks first, then size the budget so every int8 bucket
        # fits with zero slack to spare — the f32 artifact's 4x weight
        # residency must then push its top buckets over
        _, probe = plan_serve_buckets(
            engines["int8"], budget_bytes=1 << 50, margin=0.0,
            weight_bytes=int8_w,
        )
        max_peak8 = max(
            r.detail["program_peak_bytes"] for r in probe.reports
        )
        budget = int8_w + max_peak8 + 4096
        planner = {"budget_bytes": int(budget),
                   "per_replica_hbm_drop_bytes": int(f32_w - int8_w)}
        for name, w in (("f32", f32_w), ("int8", int8_w)):
            fitting, outcome = plan_serve_buckets(
                engines[name], budget_bytes=budget, margin=0.0,
                weight_bytes=w,
            )
            planner[name] = {
                "weight_resident_bytes": int(w),
                "rows": [
                    {
                        "batch": r.candidate.batch,
                        "program_peak_bytes": int(
                            r.detail["program_peak_bytes"]
                        ),
                        "weight_resident_bytes": int(
                            r.detail["weight_resident_bytes"]
                        ),
                        "total_bytes": int(r.peak_bytes),
                        "fits": bool(r.fits),
                    }
                    for r in outcome.reports
                ],
            }
            planner[f"{name}_buckets_fit"] = [int(b) for b in fitting]
        if not len(planner["int8_buckets_fit"]) > len(
            planner["f32_buckets_fit"]
        ):
            raise RuntimeError(
                f"int8 ladder {planner['int8_buckets_fit']} did not "
                f"outgrow f32 {planner['f32_buckets_fit']} under budget "
                f"{budget}"
            )

        # ---- mismatch drill: f32-stamped calibration grafted into a
        # copy of the int8 artifact — fail-closed must be OBSERVED
        mm_path = os.path.join(tmp, "mismatch.mgproto")
        shutil.copy(int8_path, mm_path)
        embed_calibration(mm_path, calib_f32)
        mm_engine = ServingEngine.from_artifact(mm_path, buckets=buckets)
        drill = {
            "quant_mismatch_total": registry.counter(
                sm.QUANT_MISMATCHES
            ).value(),
            "degraded": bool(mm_engine.gate.degraded),
            "swap_reject": verify_head(mm_engine.gate),
        }

        record = {
            "metric": "quant",
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "backend": jax.default_backend(),
            "config": {
                "tiny": True,
                "classes": classes,
                "per_class": per_class,
                "buckets": list(buckets),
                "kinds": list(kinds),
                "severities": list(severities),
                "seed": seed,
                "auroc_rederive_tol": 1e-9,
            },
            "weights": {
                "rows": [dict(r) for r in q.report],
                "f32_total": int(f32_w),
                "int8_total": int(int8_w),
                "reduction": round(reduction, 3),
                "num_quantized": qc["num_quantized"],
                "num_skipped": qc["num_skipped"],
            },
            "parity": parity,
            "planner": planner,
            "trust": trust,
            "floors": {
                "weight_reduction_min": 3.0,
                "tolerance": tol,
                "auroc_delta_limit": 0.05,
                "answered_accuracy_delta_limit": 0.10,
                "px_divergence_limit": mc.px_divergence_limit,
            },
            "drill": drill,
        }
        # self-gate with the SAME suite `check --quant` applies — a record
        # this measure would commit must already pass its own re-derivation
        from mgproto_tpu.cli.telemetry import quant_gates

        gates = quant_gates(record)
        record["gates"] = gates
        if not gates["ok"]:
            failing = [r for r in gates["rows"] if not r["ok"]]
            raise RuntimeError(f"quant self-gate failed: {failing}")
        return record
    finally:
        set_current_registry(prev)
        shutil.rmtree(tmp, ignore_errors=True)


def _fail(error_obj: dict) -> None:
    """Terminal failure path: emit the live diagnostics, then — if a watcher
    window ever captured a real number — the cached result as the final line
    so the driver artifact is never numberless when a genuine number exists.
    Exit 0 iff a FRESH (age <= BENCH_CACHED_MAX_AGE_S) cached number was
    emitted; a stale one is still emitted for reference but flagged
    "stale": true with exit 1, so a long-dead relay cannot keep reporting
    months-old numbers as a healthy run (ADVICE r5)."""
    cached = _cached_result()
    if cached is None:
        _emit(error_obj)
        raise SystemExit(1)
    # a cached number must never be presentable as live: the explicit
    # cached flag plus the live failure — including the STRUCTURED probe
    # failure when the probe gate is what failed — ride on the final line
    # itself, so a trajectory reader sees the flatline's cause in-band
    cached["cached"] = True
    cached["live_error"] = error_obj.get("error")
    if error_obj.get("probe_failure") is not None:
        cached["probe_failure"] = error_obj["probe_failure"]
    age = _cached_age_s(cached)
    cached["cached_age_s"] = None if age == float("inf") else round(age, 1)
    if age > CACHED_MAX_AGE_S:
        cached["stale"] = True
        _emit(cached)
        raise SystemExit(1)
    _emit(cached)
    raise SystemExit(0)


def _probe_gate():
    """Cheap backend-health gate before any flagship attempt. Emits one JSON
    line per probe; returns (ok, last_failed_probe_record_or_None). Probes
    whatever platform this process would get (TPU in production, CPU in CI).
    The failure record rides into `_fail` so a cached-fallback line carries
    the STRUCTURED probe diagnosis, not just prose — BENCH_r03-r05 served a
    cached number whose probe story lived only in earlier log lines, and the
    round-over-round trajectory flatlined invisibly."""
    if os.environ.get("BENCH_SKIP_PROBE"):
        _emit({
            # every in-progress line carries "error": if a kill makes it the
            # LAST line, it must read as a self-describing diagnostic
            "error": "in progress; killed after probe skip, before attempts",
            "event": "probe_skipped",
            "reason": "BENCH_SKIP_PROBE set",
        })
        return True, None
    from mgproto_tpu.probe import probe_once

    record = None
    for i in range(1, max(PROBE_ATTEMPTS, 1) + 1):
        record = probe_once(PROBE_TIMEOUT_S)
        line = {
            "error": (
                "in progress; killed after successful probe, before attempts"
                if record["ok"] else "backend probe failed"
            ),
            "event": "probe",
            "probe_attempt": i,
            **record,
        }
        _emit(line)
        if record["ok"]:
            return True, None
        if i <= PROBE_ATTEMPTS - 1:
            time.sleep(10)
    return False, {"attempts": max(PROBE_ATTEMPTS, 1), **(record or {})}


def main() -> None:
    _emit({
        "error": "bench started but was killed before any attempt completed",
        "event": "start",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "batch": BATCH,
        "iters": ITERS,
        "attempt_timeout_s": ATTEMPT_TIMEOUT_S,
        "deadline_s": DEADLINE_S,
    })
    if _ENV_ERRORS or BATCH <= 0 or ITERS <= 0:
        # deterministic misconfig: report immediately, don't retry 12 children
        detail = "; ".join(_ENV_ERRORS) or (
            f"invalid BENCH_BATCH={BATCH} / BENCH_ITERS={ITERS}: "
            f"both must be > 0"
        )
        _emit({"error": detail, "attempts": 0, "errors": {}})
        raise SystemExit(1)

    probe_ok, probe_failure = _probe_gate()
    if not probe_ok:
        _fail({
            "error": (
                "backend unreachable: a tiny-jit child probe failed "
                f"{PROBE_ATTEMPTS}x within {PROBE_TIMEOUT_S}s each — relay "
                "down; flagship attempts not started (they would only burn "
                "the window rediscovering the hang)"
            ),
            "attempts": 0,
            "errors": {"probe": "see probe event lines above"},
            "probe_failure": probe_failure,
        })

    plan = [("unfused", "unfused", BATCH), ("fused", "fused", BATCH)]
    if BEST_BATCH > 0 and BEST_BATCH != BATCH:
        # throughput-optimal batch from the on-device sweep (PERF.md); the
        # two reference-batch paths come FIRST so a deadline-truncated run
        # still records the head-to-head at the reference's batch 80
        plan.append((f"fused_b{BEST_BATCH}", "fused", BEST_BATCH))
    if BEST_BATCH > 0 and F32_BATCH > 0:
        # the f32-vs-bf16 head-to-head at the throughput-optimal batch:
        # the flagship IS bf16 (flagship_config), so the dtype win needs a
        # measured f32 line beside it or it stays a cost-model claim.
        # Bonus entry (2 attempts max), gated on BEST_BATCH like the other
        # bonus lines so CI smoke runs skip it.
        plan.append((f"fused_f32_b{F32_BATCH}", "fused_f32", F32_BATCH))
    if BEST_BATCH > 0 and REMAT_BATCH > 0:
        # the r4 batch-512 DNF, retried with layer1-only selective remat:
        # rematting just the cheap-but-wide 112^2 stage trades ~12% of the
        # FLOPs for the biggest slice of activation HBM (PERF.md) — the
        # cheapest way to make 512 fit. Bonus entry: 2 attempts max; gated
        # on BEST_BATCH too because BEST_BATCH=0 marks a CI smoke run at
        # toy sizes where a 512-batch flagship compile has no business.
        plan.append(
            (f"fused_b{REMAT_BATCH}_remat_l1", "fused_remat_l1", REMAT_BATCH)
        )
    results = {}
    errors = {}
    attempts_total = 0
    partial_line = None
    for name, measure, batch in plan:
        result, err, attempts = robust_measure(
            name, measure, batch,
            # once a partial result exists, re-flush it after every
            # in-progress line so the last line stays a real number
            reemit=(lambda: _emit(partial_line)) if partial_line else None,
        )
        attempts_total += attempts
        if result is not None:
            results[name] = result
        else:
            errors[name] = err
        if results:
            # flush the best-known RESULT now: a kill during the next path
            # still leaves a real number as the last parseable line
            is_final = name == plan[-1][0]
            partial_line = _summary(results, errors, attempts_total,
                                    partial=not is_final)
            _emit(partial_line)

    if not results:
        _fail({
            "error": "all scoring paths failed after retries",
            "attempts": attempts_total,
            "errors": errors,
        })


if __name__ == "__main__":
    if len(sys.argv) in (3, 4) and sys.argv[1] == "--measure":
        # child mode: one measurement, result JSON on the last stdout line.
        # Optional 3rd operand overrides the batch (the best-batch plan
        # entry); BENCH_BATCH env still works for plain 2-operand calls.
        measure = sys.argv[2]
        if measure == "em":
            # hermetic compile-only microbench (no probe, CPU-friendly)
            print(json.dumps(measure_em()))
            raise SystemExit(0)
        if measure == "overlap":
            # hermetic trunk/bank-split microbench (no probe, CPU-friendly)
            print(json.dumps(measure_overlap()))
            raise SystemExit(0)
        if measure == "dtype":
            # hermetic f32-vs-bf16 byte microbench, with the cached-
            # fallback/staleness degrade (ISSUE 12)
            _measure_with_cached_fallback(measure_dtype, "dtype_bench.json")
        if measure == "coldstart":
            # hermetic cold-vs-warm replica-start microbench (AOT
            # executable cache), same degrade machinery (ISSUE 13)
            _measure_with_cached_fallback(
                measure_coldstart, "coldstart_bench.json"
            )
        if measure == "weakscale":
            # hermetic 1->2->4->8 weak-scaling curve (ISSUE 14), same
            # cached-fallback/staleness degrade machinery
            _measure_with_cached_fallback(
                measure_weakscale, "weakscale_bench.json"
            )
        if measure == "quant":
            # hermetic int8 weight-only serving harness (ISSUE 20), same
            # cached-fallback/staleness degrade machinery
            _measure_with_cached_fallback(measure_quant, "quant_bench.json")
        if measure == "weakscale_probe":
            # child mode of measure_weakscale: ONE chip count, whose
            # device pool the parent fixed via XLA_FLAGS before spawn
            print(json.dumps(measure_weakscale_probe(int(sys.argv[3]))))
            raise SystemExit(0)
        if len(sys.argv) == 4:
            BATCH = int(sys.argv[3])
        if BATCH <= 0:
            raise SystemExit(f"batch must be > 0, got {BATCH}")
        valid = (
            "unfused", "fused", "fused_remat_l1", "fused_f32",
            "eval_unfused", "eval_fused",
        )
        if measure not in valid:
            raise SystemExit(f"--measure must be one of {valid}, got {measure!r}")
        print(json.dumps(run_config(
            fused=measure in ("fused", "fused_remat_l1", "fused_f32",
                              "eval_fused"),
            eval_mode=measure.startswith("eval"),
            remat_stages=("layer1",) if measure == "fused_remat_l1" else (),
            # the f32 head-to-head: same fused path, f32 trunk — the
            # measured counterpart of the bf16 flagship (ISSUE 12)
            compute_dtype="float32" if measure == "fused_f32"
            else "bfloat16",
        )))
    else:
        main()
