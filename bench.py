"""Throughput benchmark: steady-state MGProto train step, images/sec/chip.

Measures the flagship recipe (ResNet-34 + CUB-200 shapes, batch 80 — the
reference's default, reference settings.py:22 / main.py:22) in its HEAVIEST
steady state: joint phase, mine loss on, memory enqueue on, and the EM update
fully active every iteration (reference update_interval=1, model.py:171, with
all 200 class queues full — the post-epoch-35 regime).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

`vs_baseline` compares against an ESTIMATED single-A100 throughput of the
reference PyTorch implementation (never measured in-repo, BASELINE.md:
"Throughput ... never measured"): ~350 img/s for R34-224 fwd+bwd+density —
bounded in practice by the reference's python-loop memory enqueue
(reference model.py:228-252) and python-loop EM over 200 classes
(model.py:281-298). The driver north star is >=6x that on a v5e-8
(BASELINE.json.north_star); this bench runs on ONE chip.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

A100_EST_IMAGES_PER_SEC = 350.0

BATCH = 80
WARMUP = 3
ITERS = 10


def run_config(fused: bool) -> float:
    """Steady-state images/sec for one scoring-path configuration."""
    from mgproto_tpu.config import Config, ModelConfig
    from mgproto_tpu.engine.train import Trainer

    cfg = Config(
        model=ModelConfig(
            arch="resnet34",
            num_classes=200,
            pretrained=False,
            # bf16 trunk on the MXU; params/BN-stats/density/losses stay f32
            compute_dtype="bfloat16",
            # XLA matmul+top_k vs the fused Pallas kernel — measured head to
            # head below, best wins
            fused_scoring=fused,
        )
    )
    trainer = Trainer(cfg, steps_per_epoch=100, donate=True)
    state = trainer.init_state(jax.random.PRNGKey(0))

    # steady state: all class queues full + touched, so EM is fully active
    mem = state.memory
    rng = jax.random.PRNGKey(1)
    feats = jax.random.uniform(rng, mem.feats.shape, jnp.float32)
    feats = feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)
    state = state.replace(
        memory=mem._replace(
            feats=feats,
            length=jnp.full_like(mem.length, mem.capacity),
            cursor=jnp.zeros_like(mem.cursor),
            updated=jnp.ones_like(mem.updated),
        )
    )

    host = np.random.RandomState(0)
    images = jnp.asarray(
        host.rand(BATCH, cfg.model.img_size, cfg.model.img_size, 3),
        jnp.float32,
    )
    labels = jnp.asarray(
        host.randint(0, cfg.model.num_classes, size=(BATCH,)), jnp.int32
    )

    def step(s):
        s, m = trainer.train_step(
            s, images, labels, use_mine=True, update_gmm=True, warm=False
        )
        # keep EM active every iteration (enqueue alone re-marks only the
        # label classes)
        return s.replace(
            memory=s.memory._replace(updated=jnp.ones_like(s.memory.updated))
        ), m

    # NB: a host readback (device_get of a scalar) is the sync point; under
    # tunneled device platforms block_until_ready can return before the device
    # actually finishes, which inflates throughput ~1000x.
    for _ in range(WARMUP):
        state, metrics = step(state)
    float(jax.device_get(metrics.loss))

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, metrics = step(state)
    float(jax.device_get(metrics.loss))
    int(jax.device_get(state.step))
    dt = time.perf_counter() - t0
    return BATCH * ITERS / dt


def main() -> None:
    value = max(run_config(fused=False), run_config(fused=True))
    print(
        json.dumps(
            {
                "metric": "mgproto_r34_cub_train_step_throughput",
                "value": round(value, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(value / A100_EST_IMAGES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
